"""Inference engine: jit-compiled prefill and decode steps + generate loops.

Realizes the reference's planned "Distributed Inference Engine"
(/root/reference/CLAUDE.md:19) the TPU way:

* One compiled prefill program (full-prompt forward, cache write) and one
  compiled decode program (single-token step). Both donate the KV cache so
  XLA updates it in place in HBM.
* A fused generate path (`lax.scan` over decode steps inside one jit) keeps
  the whole token loop device-resident — zero host round trips per token —
  which is what the tokens/sec/chip metric (BASELINE.json) rewards.
* Batch shapes are static: variable-length prompts are right-padded; padded
  key slots sit at positions the causal mask can never reach (a query at
  position p attends only j <= p, and pads land at j >= true_len > p), and
  decode overwrites them before they ever become visible.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from butterfly_tpu.core.config import ModelConfig, RuntimeConfig
from butterfly_tpu.engine.sampling import SamplingParams, sample
from butterfly_tpu.models.common import KVCache, Model, forward, init_cache


@dataclass
class GenerateResult:
    tokens: np.ndarray          # [B, max_new] generated ids (post-stop garbage masked to pad)
    lengths: np.ndarray         # [B] number of valid generated tokens
    prompt_lengths: np.ndarray  # [B]


@dataclass
class SpeculativeResult:
    tokens: np.ndarray       # [n] generated ids (stop-truncated)
    forwards: int            # device forwards taken (prefill + verifies)
    accepted_drafts: int     # draft tokens accepted across all verifies

    @property
    def tokens_per_forward(self) -> float:
        return len(self.tokens) / max(1, self.forwards)


def _accept_drafts(draft, greedy) -> List[int]:
    """Greedy draft acceptance — the host fast path of the shared
    semantics sampling.speculative_accept implements on device for the
    serving spec block (the two must not drift; the temp-0 rows of the
    device kernel reproduce exactly this): emit greedy[0] (the token
    after `cur`), then keep accepting while draft[i] == greedy[i], each
    acceptance also emitting greedy[i+1]. Token-for-token identical to
    plain greedy decode by construction."""
    emitted = [int(greedy[0])]
    for i, d in enumerate(draft):
        if d != int(greedy[i]):
            break
        emitted.append(int(greedy[i + 1]))
    return emitted


def _ngram_draft(history, gamma: int, ngram: int):
    """Prompt-lookup draft: find the most recent earlier occurrence of
    the trailing `ngram` tokens and propose what followed it. Pads with
    zeros on no match / short continuation (padding simply gets
    rejected by the verify step — no special casing)."""
    draft = []
    if len(history) > ngram:
        tail = history[-ngram:]
        # scan right-to-left for the most recent match
        for i in range(len(history) - ngram - 1, -1, -1):
            if history[i:i + ngram] == tail:
                draft = history[i + ngram:i + ngram + gamma]
                break
    return draft + [0] * (gamma - len(draft))


class InferenceEngine:
    """Single-program inference over a (possibly sharded) param pytree.

    Sharded use: pass `shardings` pytrees for params/cache (from the
    partitioner); jit then compiles one SPMD program over the active mesh.
    """

    def __init__(self, model: Model, params, runtime: Optional[RuntimeConfig] = None,
                 mesh=None, num_microbatches: Optional[int] = None,
                 use_flash_prefill: Optional[bool] = None,
                 virtual_stages: int = 1):
        self.model = model
        self.cfg = model.cfg
        self.runtime = runtime or RuntimeConfig()
        # (B, max_seq) -> reusable KV buffers from the previous call;
        # bounded (FIFO) so varying shapes can't pin unbounded HBM
        from collections import OrderedDict
        self._cache_pool: "OrderedDict" = OrderedDict()
        self._cache_pool_cap = 2
        # Inference reads every weight every step: keep params in the
        # compute dtype so the decode loop streams half the HBM bytes
        # (the in-scan cast then no-ops and XLA elides it).
        self.params = cast_params(params, self.cfg)
        self.mesh = mesh
        S = mesh.shape.get("stage", 1) if mesh is not None else 1
        if virtual_stages > 1 and S > 1:
            # interleaved 1F1B-style schedule: permute the layer stack
            # once so each stage's contiguous shard holds its V
            # round-robin chunks (parallel/pipeline.py). Donating jit:
            # no transient second copy of the stack in HBM.
            from butterfly_tpu.parallel.pipeline import interleave_layers
            perm = jax.jit(
                partial(interleave_layers, num_layers=self.cfg.num_layers,
                        S=S, V=virtual_stages),
                donate_argnums=(0,))
            self.params = dict(self.params)
            self.params["layers"] = perm(self.params["layers"])
        elif S <= 1:
            virtual_stages = 1  # no stage axis: schedule knob is moot
        if use_flash_prefill is None:
            # Pallas kernels are TPU-only; under a mesh the call sites go
            # through ops/*_sharded (shard_map over data/tensor), so a
            # mesh no longer disables them.
            use_flash_prefill = jax.default_backend() == "tpu"

        # One forward callable per step kind: the plain single-program
        # forward, or the GPipe pipeline when the mesh has stage > 1.
        # Prefill steps are always fresh (new cache, positions 0..T-1), so
        # they may use the Pallas flash kernel (cfg.attn_impl contract).
        def make_fwd(cfg, fresh=False):
            # last_index: last-token-only LM head (forward docs). The
            # GPipe forward computes full logits per microbatch — it
            # ignores the hint and the caller gathers afterwards.
            if mesh is not None and mesh.shape.get("stage", 1) > 1:
                from butterfly_tpu.parallel.pipeline import pipeline_forward
                return lambda p, t, c, pos=None, last_index=None: \
                    pipeline_forward(
                        p, cfg, t, c, mesh, num_microbatches, pos,
                        fresh=fresh, virtual_stages=virtual_stages)
            return lambda p, t, c, pos=None, last_index=None: forward(
                p, cfg, t, c, pos, fresh=fresh, last_index=last_index)

        fwd = make_fwd(self.cfg)
        prefill_cfg = self.cfg.replace(attn_impl="flash") \
            if use_flash_prefill else self.cfg
        self._fwd = fwd
        self._prefill = jax.jit(
            partial(_prefill_step, make_fwd(prefill_cfg, fresh=True)),
            donate_argnums=(2,),
        )
        self._decode = jax.jit(
            partial(_decode_step, fwd),
            static_argnums=(4,),
            donate_argnums=(2,),
        )
        # Fused generate: the write-combined window variant decodes
        # decode_window tokens per outer scan step and flushes them into
        # the cache in one ragged write (models/common.py window docs);
        # the per-step variant remains for pipeline meshes (the GPipe
        # forward manages its own cache writes) and decode_window=1.
        window = self.runtime.decode_window
        if window == 0:  # auto (config.py rationale)
            window = 16 if self.runtime.kv_quant == "int8" else 1
        self._decode_window = max(1, window) if S <= 1 else 1
        if self._decode_window > 1:
            self._generate_fused = jax.jit(
                partial(_generate_fused_win, self.cfg, self._decode_window),
                static_argnums=(4, 5, 6),
                donate_argnums=(2,),
            )
        else:
            self._generate_fused = jax.jit(
                partial(_generate_fused, fwd),
                static_argnums=(4, 5),
                donate_argnums=(2,),
            )

    # -- public API ---------------------------------------------------------

    def new_cache(self, batch: int, max_seq: Optional[int] = None) -> KVCache:
        return init_cache(self.cfg, batch, max_seq or self.runtime.max_seq_len,
                          quant=self.runtime.kv_quant)

    def prefill(self, tokens: jax.Array, true_lens: jax.Array,
                cache: KVCache) -> Tuple[jax.Array, KVCache]:
        """tokens [B,Tpad] right-padded; returns (last-token logits [B,V], cache)."""
        return self._prefill(self.params, tokens, cache, true_lens)

    def decode(self, token: jax.Array, cache: KVCache, key: jax.Array,
               sp: SamplingParams) -> Tuple[jax.Array, KVCache, jax.Array]:
        return self._decode(self.params, token, cache, key, sp)

    def generate(self, prompts: Sequence[Sequence[int]],
                 sp: Optional[SamplingParams] = None,
                 seed: int = 0, fused: bool = True) -> GenerateResult:
        """End-to-end batched generation from python-list prompts."""
        sp = sp or SamplingParams()
        n_real = len(prompts)
        # The mesh's data axis shards the batch dim: pad the request count
        # to a multiple of it (dummy rows are stripped from the result).
        if self.mesh is not None:
            dp = self.mesh.shape.get("data", 1)
            if n_real % dp != 0:
                prompts = list(prompts) + [list(prompts[0])] * (
                    dp - n_real % dp)
        tokens, true_lens = pad_prompts(prompts)
        B = tokens.shape[0]
        total = tokens.shape[1] + sp.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({tokens.shape[1]}) + max_new_tokens "
                f"({sp.max_new_tokens}) = {total} exceeds the model's "
                f"max_seq_len ({self.cfg.max_seq_len})")
        # Exact KV sizing: prefill writes T slots and the decode loop
        # writes at most max(max_new, ceil(steps/C)*C) more (the windowed
        # scan rounds the step count up to a multiple of the window; its
        # tail steps write frozen tokens past `total`). Attention reads
        # the WHOLE buffer every step, so slack rows are pure HBM
        # traffic: `total + C - 1` cost 6% of the decode-loop bytes at
        # the bench shape (S 271 vs 256).
        steps = sp.max_new_tokens - 1
        iters = -(-steps // self._decode_window) if steps else 0
        max_seq = max(self.runtime.max_seq_len,
                      tokens.shape[1] + max(sp.max_new_tokens,
                                            iters * self._decode_window))
        # Reuse the previous call's (donated-through) cache buffers when
        # the shape matches: a fresh pool pays allocation + memset of
        # ~GBs per call, and stale K/V is harmless — prefill overwrites
        # positions 0..T-1 and the causal mask never reaches past each
        # row's written length.
        cache = self._cache_pool.pop((B, max_seq), None)
        if cache is None:
            cache = self.new_cache(B, max_seq)
            if self.mesh is not None:
                from butterfly_tpu.parallel.partition import shard_cache
                cache = shard_cache(cache, self.cfg, self.mesh)
        key, first_key, loop_key = jax.random.split(jax.random.PRNGKey(seed), 3)

        with self._mesh_ctx():
            logits, cache = self.prefill(jnp.asarray(tokens),
                                         jnp.asarray(true_lens), cache)
            first = sample(logits, first_key, sp)

            if fused:
                if self._decode_window > 1:
                    # static flag: every row flushes at the same offset
                    # (equal prompt lengths) -> one aliasable
                    # scalar-offset cache write per flush group
                    uniform = bool(np.all(true_lens == true_lens[0]))
                    out, lens, cache = self._generate_fused(
                        self.params, first, cache, loop_key, sp,
                        sp.max_new_tokens, uniform)
                else:
                    out, lens, cache = self._generate_fused(
                        self.params, first, cache, loop_key, sp,
                        sp.max_new_tokens)
                out, lens = np.asarray(out), np.asarray(lens)
            else:
                toks = [np.asarray(first)]
                cur = first
                key = loop_key
                for _ in range(sp.max_new_tokens - 1):
                    key, sub = jax.random.split(key)
                    cur, cache, _ = self.decode(cur, cache, sub, sp)
                    toks.append(np.asarray(cur))
                out = np.stack(toks, axis=1)
                lens = _stop_lengths(out, sp.stop_token)
                out = _mask_after_stop(out, lens, sp.stop_token)
        self._cache_pool[(B, max_seq)] = cache
        while len(self._cache_pool) > self._cache_pool_cap:
            self._cache_pool.popitem(last=False)  # FIFO-evict (frees HBM)
        return GenerateResult(tokens=out[:n_real], lengths=lens[:n_real],
                              prompt_lengths=np.asarray(true_lens)[:n_real])

    def generate_long(self, prompt: Sequence[int],
                      sp: Optional[SamplingParams] = None,
                      seed: int = 0, impl: str = "ring") -> GenerateResult:
        """Long-context generation over the mesh's `seq` axis (SURVEY §3
        call stack 5): sequence-parallel prefill (parallel/sequence.py
        sp_forward — ring attention or Ulysses) leaves the prompt's KV
        sharded over `seq` where it was computed; decode steps
        (sp_decode_step) merge per-device partial attention with
        [B,Nq,H]-sized collectives, so the long prefix is never
        regathered. Single sequence (the long-context shape); the prompt
        is right-padded to a multiple of the seq axis and the pad K/V is
        masked out of every decode step (prefill needs no mask: pads sit
        at positions causality already excludes).

        runtime.kv_quant="int8" rides straight through (ISSUE 20): the
        sharded prefix and the replicated suffix both hold codes+scales
        and every attention read dequantizes in-kernel, so the long
        prefix costs a quarter of the bf16 HBM.

        CLI surface: `butterfly generate --seq-parallel N`.
        """
        sp = sp or SamplingParams()
        if self.mesh is None or self.mesh.shape.get("seq", 1) <= 1:
            raise ValueError(
                "generate_long needs a mesh with a seq axis > 1 "
                "(CLI: --seq-parallel N)")
        if self.mesh.shape.get("stage", 1) > 1:
            raise NotImplementedError(
                "seq-parallel generation does not compose with pipeline "
                "stages (stage > 1): sp_forward runs the whole layer "
                "stack on every seq shard")
        from butterfly_tpu.models.common import init_cache
        from butterfly_tpu.parallel.sequence import (sp_decode_step,
                                                     sp_forward)

        N = self.mesh.shape["seq"]
        ids = list(prompt)
        true_len = len(ids)
        total = true_len + sp.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt ({true_len}) + max_new_tokens "
                f"({sp.max_new_tokens}) = {total} exceeds the model's "
                f"max_seq_len ({self.cfg.max_seq_len})")
        pad = -(-true_len // N) * N
        tokens = np.zeros((1, pad), np.int32)
        tokens[0, :true_len] = np.asarray(ids, np.int32)
        plen = jnp.asarray([true_len], jnp.int32)

        key, first_key, loop_key = jax.random.split(
            jax.random.PRNGKey(seed), 3)
        mesh = self.mesh
        kvq = self.runtime.kv_quant
        # jit wrappers cached per engine (keyed by impl + kv_quant):
        # rebuilding them per call would re-trace and recompile both
        # programs each time
        if not hasattr(self, "_sp_programs"):
            self._sp_programs = {}
        if (impl, kvq) not in self._sp_programs:
            self._sp_programs[(impl, kvq)] = (
                jax.jit(lambda p, t: sp_forward(p, self.cfg, t, mesh,
                                                impl=impl, kv_quant=kvq)),
                jax.jit(lambda p, t, pos, pre, suf, pl: sp_decode_step(
                    p, self.cfg, t, pos, pre, suf, mesh, prefix_len=pl)))
        prefill, step = self._sp_programs[(impl, kvq)]
        with self._mesh_ctx():
            logits, prefix = prefill(self.params, jnp.asarray(tokens))
            cur = sample(logits[:, true_len - 1, :], first_key, sp)
            # replicated suffix cache sized for the whole decode run
            # (quantized alongside the prefix so both segments read the
            # same representation the dense int8 path reads back)
            suffix = init_cache(self.cfg, 1, sp.max_new_tokens, quant=kvq)
            # Dispatch-ahead decode: keep up to runtime.inflight_blocks
            # sp_decode_step dispatches chained on the DEVICE token
            # before reading any back — the per-token int(np.asarray)
            # round trip otherwise serializes host and device every
            # step (the serving scheduler's _inflight pattern, single-
            # sequence edition). Positions depend only on the dispatch
            # count, never on token values, so dispatching runs ahead
            # of the host's stop-token check; tokens dispatched past a
            # stop are discarded at drain, and the dispatch count is
            # bounded by max_new_tokens - 1 so the suffix cache cannot
            # overflow.
            depth = max(1, self.runtime.inflight_blocks)
            pending = deque([cur])
            out: List[int] = []
            n_disp = 0  # decode steps dispatched so far
            key = loop_key
            while pending:
                while len(pending) <= depth and \
                        n_disp < sp.max_new_tokens - 1:
                    positions = jnp.asarray([[true_len + n_disp]],
                                            jnp.int32)
                    logits, suffix = step(self.params, cur[:, None],
                                          positions, prefix, suffix, plen)
                    key, sub = jax.random.split(key)
                    cur = sample(logits, sub, sp)
                    pending.append(cur)
                    n_disp += 1
                tok = int(np.asarray(pending.popleft())[0])
                out.append(tok)
                if sp.stop_token >= 0 and tok == sp.stop_token:
                    break  # in-flight steps past the stop are discarded

        toks = np.asarray(out, np.int32)[None]
        lens = _stop_lengths(toks, sp.stop_token)
        return GenerateResult(tokens=_mask_after_stop(toks, lens,
                                                      sp.stop_token),
                              lengths=lens,
                              prompt_lengths=np.asarray([true_len]))

    def generate_speculative(self, prompt: Sequence[int],
                             sp: Optional[SamplingParams] = None,
                             gamma: int = 4, ngram: int = 2,
                             seed: int = 0) -> "SpeculativeResult":
        """Generation with prompt-lookup speculative decoding.

        Drafts `gamma` tokens per step by matching the last `ngram`
        generated tokens against the sequence so far (the model-free
        "prompt lookup" scheme) and verifies the whole draft in ONE
        (gamma+1)-token warm forward. Accepted drafts advance the
        sequence several tokens per forward. At temperature 0 the
        output is token-for-token IDENTICAL to plain greedy decode
        (`_accept_drafts` fast path); at temperature > 0 each draft is
        accepted with probability p(draft) and the first rejection
        resamples from the residual (sampling.speculative_accept — the
        Leviathan et al. rejection-sampling correction, exact for the
        one-hot prompt-lookup proposal), so the output DISTRIBUTION
        equals plain sampling. Either way speculation only changes how
        many forwards the tokens take.

        Correctness of the KV cache under rejection: a verify step
        writes K/V for every draft position; rejected positions hold
        stale K/V, but the next verify starts at the first rejected
        position and rewrites all of them before any query can attend
        that far (write-then-attend in attention_block), so stale
        entries are never visible.

        Single-sequence, host-looped (per-row accept counts diverge;
        the BATCHED multi-slot edition lives in the serving engine's
        spec block — engine/serving.py _spec_scan).
        """
        sp = sp or SamplingParams()
        if gamma < 1 or ngram < 1:
            raise ValueError("gamma and ngram must be >= 1")
        if self.mesh is not None and (self.mesh.shape.get("data", 1) > 1
                                      or self.mesh.shape.get("stage", 1) > 1):
            # one sequence can't be data-sharded, and the GPipe forward
            # has no single-microbatch warm-verify path
            raise NotImplementedError(
                "speculative decoding supports tensor/expert meshes only")

        tokens, true_lens = pad_prompts([list(prompt)])
        total = tokens.shape[1] + sp.max_new_tokens
        if total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds max_seq_len")
        # + gamma slack: the last verify may write past `total`
        cache = self.new_cache(1, max(self.runtime.max_seq_len,
                                      total + gamma))
        if self.mesh is not None:
            from butterfly_tpu.parallel.partition import shard_cache
            cache = shard_cache(cache, self.cfg, self.mesh)

        stochastic = not sp.is_greedy
        key, first_key = jax.random.split(jax.random.PRNGKey(seed))
        with self._mesh_ctx():
            logits, cache = self.prefill(jnp.asarray(tokens),
                                         jnp.asarray(true_lens), cache)
            cur = int(np.asarray(sample(logits, first_key, sp))[0]) \
                if stochastic else int(jnp.argmax(logits[0]))
        history = list(prompt) + [cur]
        out = [cur]
        forwards = 1  # the prefill produced the first token
        accepted_total = 0

        # greedy keeps its argmax-on-device program (+_accept_drafts
        # fast path, byte-identical to plain greedy decode); sampling
        # fetches the verify logits and runs the rejection-sampling
        # correction (the shared speculative_accept kernel)
        verify = self._verify_program(gamma, logits=stochastic)
        temps = jnp.asarray([sp.temperature], jnp.float32)
        while len(out) < sp.max_new_tokens and \
                not (sp.stop_token >= 0 and out[-1] == sp.stop_token):
            draft = _ngram_draft(history, gamma, ngram)
            pos0 = len(history) - 1  # cur's absolute position
            toks = jnp.asarray([[cur] + draft], jnp.int32)
            positions = pos0 + jnp.arange(gamma + 1)[None, :]
            with self._mesh_ctx():
                ver, cache = verify(self.params, toks, cache, positions)
            forwards += 1

            if stochastic:
                from butterfly_tpu.engine.sampling import speculative_accept
                key, sub = jax.random.split(key)
                em, n_acc = speculative_accept(
                    ver, jnp.asarray([draft], jnp.int32), sub, temps,
                    sp.top_k, sp.top_p)
                n = int(np.asarray(n_acc)[0]) + 1
                emitted = np.asarray(em)[0, :n].tolist()
            else:
                emitted = _accept_drafts(draft, np.asarray(ver[0]))
            accepted_total += len(emitted) - 1
            # valid cache entries: cur + the accepted drafts
            new_len = pos0 + len(emitted)
            cache = cache._replace(
                length=jnp.asarray([new_len], jnp.int32))
            for t in emitted:
                out.append(t)
                history.append(t)
                if len(out) >= sp.max_new_tokens or \
                        (sp.stop_token >= 0 and t == sp.stop_token):
                    break
            cur = out[-1]

        if sp.stop_token >= 0 and sp.stop_token in out:
            out = out[:out.index(sp.stop_token) + 1]
        return SpeculativeResult(
            tokens=np.asarray(out, np.int32), forwards=forwards,
            accepted_drafts=accepted_total)

    def _verify_program(self, gamma: int, logits: bool = False):
        """jitted (gamma+1)-token warm verify. Returns per-position
        greedy next tokens [B, gamma+1] (logits=False — the greedy
        fast path keeps argmax on device) or the raw per-position
        logits [B, gamma+1, V] (logits=True — the stochastic path
        feeds them to the rejection-sampling correction). Cached per
        (gamma, flavor)."""
        if not hasattr(self, "_verify_cache"):
            self._verify_cache = {}
        cache_key = (gamma, logits)
        if cache_key not in self._verify_cache:
            fwd = self._fwd

            def step(params, toks, cache, positions, _logits=logits):
                out, cache = fwd(params, toks, cache, positions)
                if not _logits:
                    out = jnp.argmax(out, axis=-1).astype(jnp.int32)
                return out, cache

            self._verify_cache[cache_key] = jax.jit(step, donate_argnums=(2,))
        return self._verify_cache[cache_key]

    def _mesh_ctx(self):
        from butterfly_tpu.core import compat
        return compat.mesh_ctx(self.mesh)


# ---------------------------------------------------------------------------
# jitted step functions (module-level so jit caches persist across engines)
# ---------------------------------------------------------------------------

def _prefill_step(fwd, params, tokens, cache, true_lens):
    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    # last real token's logits only (forward last_index docs); paths
    # that don't honor the hint return full-T logits — gather those.
    logits, cache = fwd(params, tokens, cache, positions,
                        last_index=true_lens - 1)
    if logits.shape[1] != 1:
        logits = jnp.take_along_axis(logits, (true_lens - 1)[:, None, None],
                                     axis=1)
    cache = cache._replace(length=true_lens.astype(jnp.int32))
    return logits[:, 0, :], cache


def _decode_step(fwd, params, token, cache, key, sp: SamplingParams):
    logits, cache = fwd(params, token[:, None], cache)
    key, sub = jax.random.split(key)
    nxt = sample(logits[:, -1, :], sub, sp)
    return nxt, cache, key


def _generate_fused(fwd, params, first, cache, key,
                    sp: SamplingParams, max_new: int):
    """lax.scan over decode steps — the whole generation is one XLA program.

    Sequences that hit the stop token keep stepping (static shapes) but
    their outputs are frozen via the done mask; no recompilation, no host
    sync until the final device->host copy.
    """
    def body(carry, _):
        cur, cache, key, done = carry
        logits, cache = fwd(params, cur[:, None], cache)
        key, sub = jax.random.split(key)
        nxt = sample(logits[:, -1, :], sub, sp)
        nxt = jnp.where(done, cur, nxt)
        if sp.stop_token >= 0:
            done = done | (nxt == sp.stop_token)
        return (nxt, cache, key, done), nxt

    done0 = (first == sp.stop_token) if sp.stop_token >= 0 \
        else jnp.zeros_like(first, dtype=bool)
    (_, cache, _, _), toks = jax.lax.scan(
        body, (first, cache, key, done0), None, length=max_new - 1)
    out = jnp.concatenate([first[:, None], toks.T], axis=1)  # [B, max_new]
    lens = _stop_lengths_jnp(out, sp.stop_token)
    # The final cache is returned so the donated input cache has an
    # output to alias (otherwise XLA keeps a second full pool live for
    # the whole scan) AND so generate() can recycle the buffers for the
    # next call instead of allocating fresh pools.
    return out, lens, cache


def _generate_fused_win(cfg: ModelConfig, C: int, params, first, cache, key,
                        sp: SamplingParams, max_new: int,
                        uniform: bool = False):
    """Write-combined fused generate: C decode steps per outer scan
    iteration against (cache + prior window steps + self), then ONE
    ragged cache write for all C tokens (flush_window). Token-for-token
    identical to _generate_fused — the window steps store the cache's
    exact representation (int8 codes + scales in quant mode) and keys
    split in the same order — while amortizing the dominant whole-pool
    copy the per-step cache update costs on TPU (models/common.py
    window docs). The C steps are unrolled, so the window is a plain
    Python list of per-step K/V values — no device buffer, no carry.
    """
    from butterfly_tpu.models.common import decode_step_win, flush_window

    B = first.shape[0]
    steps = max_new - 1
    iters = -(-steps // C) if steps else 0

    def body(carry, _):
        cur, cache, key, done = carry
        toks, window = [], []
        for j in range(C):
            key, sub = jax.random.split(key)
            logits, new_kv = decode_step_win(
                params, cfg, cur[:, None], cache, window, j)
            window.append(new_kv)
            nxt = sample(logits[:, -1, :], sub, sp)
            nxt = jnp.where(done, cur, nxt)
            if sp.stop_token >= 0:
                done = done | (nxt == sp.stop_token)
            cur = nxt
            toks.append(nxt)
        cache = flush_window(cache, window, uniform=uniform)
        return (cur, cache, key, done), jnp.stack(toks)

    done0 = (first == sp.stop_token) if sp.stop_token >= 0 \
        else jnp.zeros_like(first, dtype=bool)
    carry0 = (first, cache, key, done0)
    (_, cache, *_), toks = jax.lax.scan(body, carry0, None, length=iters)
    toks = toks.reshape(iters * C, B)[:steps] if steps \
        else jnp.zeros((0, B), first.dtype)
    out = jnp.concatenate([first[:, None], toks.T], axis=1)  # [B, max_new]
    lens = _stop_lengths_jnp(out, sp.stop_token)
    return out, lens, cache


def _stop_lengths_jnp(out: jax.Array, stop: int) -> jax.Array:
    B, T = out.shape
    if stop < 0:
        return jnp.full((B,), T, jnp.int32)
    hit = out == stop
    any_hit = hit.any(axis=1)
    first_hit = jnp.argmax(hit, axis=1)
    return jnp.where(any_hit, first_hit + 1, T).astype(jnp.int32)


def _stop_lengths(out: np.ndarray, stop: int) -> np.ndarray:
    return np.asarray(_stop_lengths_jnp(jnp.asarray(out), stop))


def _mask_after_stop(out: np.ndarray, lens: np.ndarray, stop: int) -> np.ndarray:
    if stop < 0:
        return out
    mask = np.arange(out.shape[1])[None, :] >= lens[:, None]
    out = out.copy()
    out[mask] = stop
    return out


def cast_params(params, cfg: ModelConfig):
    """One-time cast of the weight pytree to the compute dtype.

    Device-resident cast (jit, donating the source) so a 70B f32 tree
    never round-trips the host; sharded inputs keep their shardings.
    """
    target = jnp.dtype(cfg.dtype)
    leaves = jax.tree.leaves(params)
    if all(a.dtype == target or not jnp.issubdtype(a.dtype, jnp.floating)
           for a in leaves):
        return params

    @partial(jax.jit, donate_argnums=(0,))
    def cast(p):
        return jax.tree.map(
            lambda a: a.astype(target)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, p)

    return cast(params)


def pad_prompts(prompts: Sequence[Sequence[int]], pad_id: int = 0
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Right-pad variable-length prompts to a rectangle."""
    lens = np.asarray([len(p) for p in prompts], np.int32)
    T = int(lens.max())
    out = np.full((len(prompts), T), pad_id, np.int32)
    for i, p in enumerate(prompts):
        out[i, :len(p)] = np.asarray(p, np.int32)
    return out, lens
