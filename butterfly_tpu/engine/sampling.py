"""Token samplers: greedy, temperature, top-k, top-p.

Pure functions of (logits, key, params) so they live inside the jitted
decode step — no host round trip per token. All filtering is done with
static-shape sorts/masks (no dynamic shapes under jit, per XLA semantics).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => disabled
    top_p: float = 1.0         # 1.0 => disabled
    max_new_tokens: int = 128
    stop_token: int = -1       # -1 => none

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_logits(scaled: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """The temperature-scaled logits after the static top-k/top-p
    filters — the distribution every sampling path (plain decode,
    speculative accept, residual resample) must agree on."""
    if top_k > 0:
        scaled = _apply_top_k(scaled, top_k)
    if top_p < 1.0:
        scaled = _apply_top_p(scaled, top_p)
    return scaled


def speculative_accept(logits: jax.Array, drafts: jax.Array, key: jax.Array,
                       temps: jax.Array, top_k: int, top_p: float,
                       spec_mask: jax.Array = None,
                       q_logits: jax.Array = None):
    """Batched draft acceptance with the rejection-sampling correction
    (Leviathan et al. 2023), for one-hot OR real proposal
    distributions.

    logits [S, C, V] are a verify forward's per-position target logits
    (C = gamma + 1: position i is the next-token distribution after the
    i-th context token); drafts [S, gamma] the proposed tokens; temps
    [S] per-slot temperatures (0 = greedy row).

    Per position i the target distribution p_i is EXACTLY the one plain
    decode samples from (temperature-scaled, top-k/top-p filtered —
    _filter_logits). The proposal q_i:

    * q_logits None — DETERMINISTIC drafts (prompt lookup / greedy
      draft models): q is a point mass at the draft, and
      accept-with-prob min(1, p/q) reduces to accepting d_i with
      probability p_i(d_i); the first rejection resamples from the
      residual p_i with d_i masked out, renormalized.
    * q_logits [S, gamma, V] — REAL drafts (an on-device draft model,
      models/draft.py): the proposal logits the drafts were actually
      sampled from, ALREADY temperature-scaled and filtered exactly as
      the drafter sampled (the draft source passes its own
      _filter_logits output through, so p and q are scored on
      consistent supports). The full Leviathan rule applies: accept
      d_i w.p. min(1, p_i(d_i)/q_i(d_i)); the first rejection
      resamples from the normalized residual (p_i - q_i)+. Wherever a
      rejection can occur at all (p(d) < q(d)) the residual has mass
      — tokens with p > q exist because both distributions sum to 1 —
      so the degenerate empty-residual row is unreachable, the same
      argument as the one-hot case below.

    When every draft is accepted, one bonus token samples from
    p_gamma. Total emitted per slot: n_acc + 1 tokens whose joint law
    equals autoregressive sampling from p — speculation changes how
    many forwards the tokens take, never their distribution. Greedy
    rows (temp 0) take the `_accept_drafts` fast path semantics
    instead regardless of q: accept while d_i == argmax_i, emit the
    argmax at the first mismatch — output byte-identical to plain
    greedy decode (the draft-model parity contract rides on this).

    p_i(d_i) == 1 (the draft is the whole filtered nucleus) always
    accepts (u ~ U[0,1) < 1), so the degenerate all--inf residual row
    is never selected.

    `spec_mask` [S] bool (None = all true): rows with False ignore
    their drafts entirely — n_acc is forced to 0 AND the emitted token
    comes from the FULL distribution, not the residual (no accept test
    ran, so a residual resample would be biased away from the draft).
    This is the per-request speculation opt-out: such a slot emits one
    exact plain-decode sample per verify round.

    Returns (emitted [S, C], n_acc [S]): emitted[:, :n_acc] are the
    accepted drafts, emitted[:, n_acc] the correction/bonus sample;
    entries past n_acc are padding. Pure jax — usable inside a jitted
    scan (the serving spec block) or eagerly (generate_speculative).
    """
    S, C, V = logits.shape
    gamma = C - 1
    if spec_mask is None:
        spec_mask = jnp.ones((S,), bool)
    stochastic = (temps > 0)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, C]
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
    scaled = _filter_logits(logits / safe_t, top_k, top_p)
    ku, kr = jax.random.split(key)
    if gamma > 0:
        probs = jax.nn.softmax(scaled[:, :gamma, :], axis=-1)
        p_draft = jnp.take_along_axis(
            probs, drafts[..., None].astype(jnp.int32), axis=-1)[..., 0]
        u = jax.random.uniform(ku, (S, gamma))
        if q_logits is None:
            # one-hot proposal: accept w.p. p(d), residual = p with the
            # tested-and-rejected draft masked out
            acc_p = p_draft
            one_hot = jax.nn.one_hot(drafts, V, dtype=bool)
            resid = jnp.where(one_hot & spec_mask[:, None, None], -jnp.inf,
                              scaled[:, :gamma, :])
        else:
            # real proposal: accept w.p. min(1, p(d)/q(d)), residual =
            # normalized (p - q)+ (categorical renormalizes for us).
            # q(d) > 0 always — d was sampled from q — the guard only
            # shields padding rows from 0/0
            q_probs = jax.nn.softmax(q_logits, axis=-1)
            q_draft = jnp.take_along_axis(
                q_probs, drafts[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            acc_p = jnp.where(q_draft > 0, p_draft / q_draft, 1.0)
            resid_p = jnp.maximum(probs - q_probs, 0.0)
            resid = jnp.where(resid_p > 0, jnp.log(resid_p), -jnp.inf)
            # opt-out rows never tested: their distribution stays full
            resid = jnp.where(spec_mask[:, None, None], resid,
                              scaled[:, :gamma, :])
        accept = jnp.where(stochastic[:, None], u < acc_p,
                           drafts == greedy_tok[:, :gamma])
        accept = accept & spec_mask[:, None]
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1).astype(jnp.int32)
        corr_logits = jnp.concatenate([resid, scaled[:, gamma:, :]], axis=1)
        pad_drafts = jnp.concatenate(
            [drafts.astype(jnp.int32), jnp.zeros((S, 1), jnp.int32)], axis=1)
    else:
        n_acc = jnp.zeros((S,), jnp.int32)
        corr_logits = scaled
        pad_drafts = jnp.zeros((S, C), jnp.int32)
    drawn = jax.random.categorical(kr, corr_logits, axis=-1).astype(jnp.int32)
    corr = jnp.where(stochastic[:, None], drawn, greedy_tok)
    emitted = jnp.where(jnp.arange(C)[None, :] < n_acc[:, None],
                        pad_drafts, corr)
    return emitted, n_acc


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    cutoff_mask = cum - probs > p
    cutoff = jnp.where(cutoff_mask, -jnp.inf, sorted_logits)
    threshold = jnp.min(jnp.where(jnp.isfinite(cutoff), cutoff, jnp.inf),
                        axis=-1, keepdims=True)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample(logits: jax.Array, key: jax.Array, sp: SamplingParams) -> jax.Array:
    """logits [B,V] float32 -> token ids [B] int32. Branches are static
    (SamplingParams is a jit-static argument)."""
    if sp.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / sp.temperature
    if sp.top_k > 0:
        logits = _apply_top_k(logits, sp.top_k)
    if sp.top_p < 1.0:
        logits = _apply_top_p(logits, sp.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
