"""Token samplers: greedy, temperature, top-k, top-p.

Pure functions of (logits, key, params) so they live inside the jitted
decode step — no host round trip per token. All filtering is done with
static-shape sorts/masks (no dynamic shapes under jit, per XLA semantics).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => disabled
    top_p: float = 1.0         # 1.0 => disabled
    max_new_tokens: int = 128
    stop_token: int = -1       # -1 => none

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _filter_logits(scaled: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """The temperature-scaled logits after the static top-k/top-p
    filters — the distribution every sampling path (plain decode,
    speculative accept, residual resample) must agree on."""
    if top_k > 0:
        scaled = _apply_top_k(scaled, top_k)
    if top_p < 1.0:
        scaled = _apply_top_p(scaled, top_p)
    return scaled


def speculative_accept(logits: jax.Array, drafts: jax.Array, key: jax.Array,
                       temps: jax.Array, top_k: int, top_p: float,
                       spec_mask: jax.Array = None,
                       q_logits: jax.Array = None):
    """Batched draft acceptance with the rejection-sampling correction
    (Leviathan et al. 2023), for one-hot OR real proposal
    distributions.

    logits [S, C, V] are a verify forward's per-position target logits
    (C = gamma + 1: position i is the next-token distribution after the
    i-th context token); drafts [S, gamma] the proposed tokens; temps
    [S] per-slot temperatures (0 = greedy row).

    Per position i the target distribution p_i is EXACTLY the one plain
    decode samples from (temperature-scaled, top-k/top-p filtered —
    _filter_logits). The proposal q_i:

    * q_logits None — DETERMINISTIC drafts (prompt lookup / greedy
      draft models): q is a point mass at the draft, and
      accept-with-prob min(1, p/q) reduces to accepting d_i with
      probability p_i(d_i); the first rejection resamples from the
      residual p_i with d_i masked out, renormalized.
    * q_logits [S, gamma, V] — REAL drafts (an on-device draft model,
      models/draft.py): the proposal logits the drafts were actually
      sampled from, ALREADY temperature-scaled and filtered exactly as
      the drafter sampled (the draft source passes its own
      _filter_logits output through, so p and q are scored on
      consistent supports). The full Leviathan rule applies: accept
      d_i w.p. min(1, p_i(d_i)/q_i(d_i)); the first rejection
      resamples from the normalized residual (p_i - q_i)+. Wherever a
      rejection can occur at all (p(d) < q(d)) the residual has mass
      — tokens with p > q exist because both distributions sum to 1 —
      so the degenerate empty-residual row is unreachable, the same
      argument as the one-hot case below.

    When every draft is accepted, one bonus token samples from
    p_gamma. Total emitted per slot: n_acc + 1 tokens whose joint law
    equals autoregressive sampling from p — speculation changes how
    many forwards the tokens take, never their distribution. Greedy
    rows (temp 0) take the `_accept_drafts` fast path semantics
    instead regardless of q: accept while d_i == argmax_i, emit the
    argmax at the first mismatch — output byte-identical to plain
    greedy decode (the draft-model parity contract rides on this).

    p_i(d_i) == 1 (the draft is the whole filtered nucleus) always
    accepts (u ~ U[0,1) < 1), so the degenerate all--inf residual row
    is never selected.

    `spec_mask` [S] bool (None = all true): rows with False ignore
    their drafts entirely — n_acc is forced to 0 AND the emitted token
    comes from the FULL distribution, not the residual (no accept test
    ran, so a residual resample would be biased away from the draft).
    This is the per-request speculation opt-out: such a slot emits one
    exact plain-decode sample per verify round.

    Returns (emitted [S, C], n_acc [S]): emitted[:, :n_acc] are the
    accepted drafts, emitted[:, n_acc] the correction/bonus sample;
    entries past n_acc are padding. Pure jax — usable inside a jitted
    scan (the serving spec block) or eagerly (generate_speculative).
    """
    S, C, V = logits.shape
    gamma = C - 1
    if spec_mask is None:
        spec_mask = jnp.ones((S,), bool)
    stochastic = (temps > 0)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, C]
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
    scaled = _filter_logits(logits / safe_t, top_k, top_p)
    ku, kr = jax.random.split(key)
    if gamma > 0:
        probs = jax.nn.softmax(scaled[:, :gamma, :], axis=-1)
        p_draft = jnp.take_along_axis(
            probs, drafts[..., None].astype(jnp.int32), axis=-1)[..., 0]
        u = jax.random.uniform(ku, (S, gamma))
        if q_logits is None:
            # one-hot proposal: accept w.p. p(d), residual = p with the
            # tested-and-rejected draft masked out
            acc_p = p_draft
            one_hot = jax.nn.one_hot(drafts, V, dtype=bool)
            resid = jnp.where(one_hot & spec_mask[:, None, None], -jnp.inf,
                              scaled[:, :gamma, :])
        else:
            # real proposal: accept w.p. min(1, p(d)/q(d)), residual =
            # normalized (p - q)+ (categorical renormalizes for us).
            # q(d) > 0 always — d was sampled from q — the guard only
            # shields padding rows from 0/0
            q_probs = jax.nn.softmax(q_logits, axis=-1)
            q_draft = jnp.take_along_axis(
                q_probs, drafts[..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            acc_p = jnp.where(q_draft > 0, p_draft / q_draft, 1.0)
            resid_p = jnp.maximum(probs - q_probs, 0.0)
            resid = jnp.where(resid_p > 0, jnp.log(resid_p), -jnp.inf)
            # opt-out rows never tested: their distribution stays full
            resid = jnp.where(spec_mask[:, None, None], resid,
                              scaled[:, :gamma, :])
        accept = jnp.where(stochastic[:, None], u < acc_p,
                           drafts == greedy_tok[:, :gamma])
        accept = accept & spec_mask[:, None]
        n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1).astype(jnp.int32)
        corr_logits = jnp.concatenate([resid, scaled[:, gamma:, :]], axis=1)
        pad_drafts = jnp.concatenate(
            [drafts.astype(jnp.int32), jnp.zeros((S, 1), jnp.int32)], axis=1)
    else:
        n_acc = jnp.zeros((S,), jnp.int32)
        corr_logits = scaled
        pad_drafts = jnp.zeros((S, C), jnp.int32)
    drawn = jax.random.categorical(kr, corr_logits, axis=-1).astype(jnp.int32)
    corr = jnp.where(stochastic[:, None], drawn, greedy_tok)
    emitted = jnp.where(jnp.arange(C)[None, :] < n_acc[:, None],
                        pad_drafts, corr)
    return emitted, n_acc


def tree_depth(width: int, nodes: int) -> int:
    """Expansion depth D of the budgeted token tree: `nodes` counts the
    root chain token plus D full fans of `width` siblings."""
    return (nodes - 1) // width


def tree_principal(d: int, width: int) -> int:
    """Chunk index of the depth-d principal node (sibling 0 of its fan;
    the root chain token at depth 0). The tree is a caterpillar: every
    depth-(d+1) fan hangs off the depth-d principal, so the principal
    chain IS the linear-gamma draft and siblings hedge each step."""
    return 0 if d == 0 else 1 + (d - 1) * width


def tree_node_index(d: int, j: int, width: int) -> int:
    """Chunk index of depth-d sibling j (d >= 1, 0 <= j < width)."""
    return 1 + (d - 1) * width + j


def tree_ancestor_matrix(width: int, nodes: int) -> np.ndarray:
    """[N, N] bool: anc[n, m] — may node n attend chunk position m?

    True for m on n's root->n ancestor path (self included). Host
    numpy, static under jit: this is the tree-attention mask's
    tree-local block, the structural difference between one verify
    forward over a token TREE and the causal chunk the linear spec
    scan dispatches."""
    N = nodes
    anc = np.zeros((N, N), dtype=bool)
    anc[0, 0] = True
    for d in range(1, tree_depth(width, nodes) + 1):
        path = [tree_principal(k, width) for k in range(d)]
        for j in range(width):
            n = tree_node_index(d, j, width)
            anc[n, path] = True
            anc[n, n] = True
    return anc


def speculative_tree_accept(logits: jax.Array, drafts: jax.Array,
                            key: jax.Array, temps: jax.Array,
                            top_k: int, top_p: float,
                            spec_mask: jax.Array = None,
                            q_logits: jax.Array = None, *,
                            width: int, nodes: int):
    """Token-TREE draft acceptance (SpecInfer-style) with the
    recursive-residual rejection correction — the output law is exactly
    the target's, like `speculative_accept`, but the proposal is a
    width-w tree of i.i.d. candidates per depth instead of one chain.

    logits [S, N, V] are ONE tree-verify forward's per-node target
    logits (N = `nodes`, chunk layout `tree_node_index`: node 0 is the
    committed chain token, depth-d sibling j at 1 + (d-1)*w + j);
    drafts [S, D, w] the candidate fans (sibling 0 = the principal);
    q_logits [S, D, V] the drafter's filtered scaled logits each
    depth's fan was i.i.d.-sampled from (one shared q per fan — the
    i.i.d. property is what makes the recursive residual law below
    exact). Tree drafting requires real q, so q_logits is mandatory
    for stochastic rows (pass it; greedy rows ignore it).

    The accept walk runs root->leaf. At depth d the target p_d is the
    filtered distribution at the parent node (the depth-(d-1)
    principal); candidates are tested in sibling order against the
    recursive residual r_0 = p_d, accept candidate j w.p.
    min(1, r_j(x)/q(x)), on rejection r_{j+1} = norm((r_j - q)+)
    (token-independent, the multi-round speculative-sampling form of
    Leviathan rejection). First accepted sibling wins:

    * principal accepted and d < D — walk continues to depth d+1;
    * non-principal accepted (or d == D) — terminal: the final token
      samples from the FULL filtered target at the accepted node
      (its own next-token distribution, the bonus sample);
    * whole fan rejected — terminal: the final token samples from the
      last residual r_w at the parent.

    Greedy rows (temp 0) accept a sibling iff it IS the parent's raw
    argmax, and the final token is the argmax at the terminal node —
    byte-identical to plain greedy decode along the realized path.
    `spec_mask` opt-out rows run no accept test and emit one sample
    from the full filtered distribution at node 0, exactly like the
    linear path's opt-out.

    Returns (emitted [S, D+1], n_acc [S], perm [S, D+1]): emitted and
    n_acc follow the `speculative_accept` contract (accepted tokens
    then the correction/bonus, entries past n_acc padding). `perm` is
    the kept-KV chunk permutation — perm[:, 0] = 0 (the chain token),
    perm[:, i] = chunk index of the i-th accepted node — so the caller
    compacts the accepted path's K/V to the contiguous committed
    positions and the rejected branches die past the length, the
    rollback-exact-by-construction pattern one dimension wider.
    """
    S, N, V = logits.shape
    w, D = width, tree_depth(width, nodes)
    assert N == nodes and drafts.shape[1] == D and drafts.shape[2] == w
    C_out = D + 1
    if spec_mask is None:
        spec_mask = jnp.ones((S,), bool)
    stochastic = (temps > 0)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, N]
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
    scaled = _filter_logits(logits / safe_t, top_k, top_p)  # [S, N, V]
    ku, kr = jax.random.split(key)
    u = jax.random.uniform(ku, (S, D, w))
    if q_logits is None:
        # greedy-only callers: a uniform stand-in keeps the stochastic
        # algebra well-defined; greedy rows never read it
        q_logits = jnp.zeros((S, D, V))
    q_probs = jax.nn.softmax(q_logits, axis=-1)  # [S, D, V]

    walking = spec_mask  # on the principal chain, not yet terminated
    n_acc = jnp.zeros((S,), jnp.int32)
    acc_stack = jnp.zeros((S, D), jnp.int32)
    perm = jnp.zeros((S, C_out), jnp.int32)  # perm[:, 0] = 0 = chain tok
    # terminal distribution: opt-out rows (never walking) keep the full
    # filtered target at node 0 — one exact plain-decode sample
    final_logits = scaled[:, 0, :]
    final_node = jnp.zeros((S,), jnp.int32)

    # D and w are tiny static ints: unrolled python loops, no scan
    for d in range(1, D + 1):
        pn = tree_principal(d - 1, w)
        p_d = jax.nn.softmax(scaled[:, pn, :], axis=-1)  # [S, V]
        q_d = q_probs[:, d - 1, :]
        r = p_d  # recursive residual, r_0 = p
        acc_here = jnp.zeros((S,), bool)
        tok_here = jnp.zeros((S,), jnp.int32)
        node_here = jnp.zeros((S,), jnp.int32)
        for j in range(w):
            tok = drafts[:, d - 1, j].astype(jnp.int32)
            r_tok = jnp.take_along_axis(r, tok[:, None], axis=1)[:, 0]
            q_tok = jnp.take_along_axis(q_d, tok[:, None], axis=1)[:, 0]
            # q(tok) > 0 always — tok was sampled from q — the guard
            # only shields greedy/padding rows from 0/0
            ratio = jnp.where(q_tok > 0, r_tok / q_tok, 1.0)
            acc_j = jnp.where(stochastic, u[:, d - 1, j] < ratio,
                              tok == greedy_tok[:, pn])
            take = walking & ~acc_here & acc_j
            tok_here = jnp.where(take, tok, tok_here)
            node_here = jnp.where(take, tree_node_index(d, j, w),
                                  node_here)
            acc_here = acc_here | take
            # residual update after a rejection — token-independent
            # (norm((r - q)+)), so one update serves every row still
            # rejecting; rows already accepted never read r again.
            # zero-mass residual (r == q exactly) is measure-zero for
            # real proposals; keep r to stay well-defined
            r_next = jnp.maximum(r - q_d, 0.0)
            mass = jnp.sum(r_next, axis=-1, keepdims=True)
            r = jnp.where(mass > 0, r_next / jnp.maximum(mass, 1e-38), r)
        acc_stack = acc_stack.at[:, d - 1].set(tok_here)
        perm = perm.at[:, d].set(jnp.where(acc_here, node_here, 0))
        n_acc = n_acc + acc_here.astype(jnp.int32)
        # fan fully rejected: final from the last residual (stochastic)
        # / the parent's argmax (greedy)
        rej = walking & ~acc_here
        resid = jnp.where(r > 0, jnp.log(r), -jnp.inf)
        final_logits = jnp.where(rej[:, None], resid, final_logits)
        final_node = jnp.where(rej, pn, final_node)
        # non-principal accepted (no children in the caterpillar) or
        # bottom of the tree: bonus from the accepted node's own
        # distribution
        term = acc_here & ((node_here != tree_principal(d, w))
                           if d < D else jnp.ones((S,), bool))
        term = walking & term
        node_scaled = jnp.take_along_axis(
            scaled, node_here[:, None, None], axis=1)[:, 0, :]
        final_logits = jnp.where(term[:, None], node_scaled, final_logits)
        final_node = jnp.where(term, node_here, final_node)
        walking = walking & acc_here & ~term

    drawn = jax.random.categorical(kr, final_logits, axis=-1)
    final_greedy = jnp.take_along_axis(
        greedy_tok, final_node[:, None], axis=1)[:, 0]
    final = jnp.where(stochastic, drawn.astype(jnp.int32), final_greedy)
    acc_pad = jnp.concatenate(
        [acc_stack, jnp.zeros((S, 1), jnp.int32)], axis=1)
    emitted = jnp.where(jnp.arange(C_out)[None, :] < n_acc[:, None],
                        acc_pad, final[:, None])
    return emitted, n_acc, perm


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    cutoff_mask = cum - probs > p
    cutoff = jnp.where(cutoff_mask, -jnp.inf, sorted_logits)
    threshold = jnp.min(jnp.where(jnp.isfinite(cutoff), cutoff, jnp.inf),
                        axis=-1, keepdims=True)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample(logits: jax.Array, key: jax.Array, sp: SamplingParams) -> jax.Array:
    """logits [B,V] float32 -> token ids [B] int32. Branches are static
    (SamplingParams is a jit-static argument)."""
    if sp.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / sp.temperature
    if sp.top_k > 0:
        logits = _apply_top_k(logits, sp.top_k)
    if sp.top_p < 1.0:
        logits = _apply_top_p(logits, sp.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
