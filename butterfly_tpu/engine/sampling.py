"""Token samplers: greedy, temperature, top-k, top-p.

Pure functions of (logits, key, params) so they live inside the jitted
decode step — no host round trip per token. All filtering is done with
static-shape sorts/masks (no dynamic shapes under jit, per XLA semantics).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0   # 0 => greedy
    top_k: int = 0             # 0 => disabled
    top_p: float = 1.0         # 1.0 => disabled
    max_new_tokens: int = 128
    stop_token: int = -1       # -1 => none

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens until cumulative prob exceeds p (always keep the first)
    cutoff_mask = cum - probs > p
    cutoff = jnp.where(cutoff_mask, -jnp.inf, sorted_logits)
    threshold = jnp.min(jnp.where(jnp.isfinite(cutoff), cutoff, jnp.inf),
                        axis=-1, keepdims=True)
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample(logits: jax.Array, key: jax.Array, sp: SamplingParams) -> jax.Array:
    """logits [B,V] float32 -> token ids [B] int32. Branches are static
    (SamplingParams is a jit-static argument)."""
    if sp.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / sp.temperature
    if sp.top_k > 0:
        logits = _apply_top_k(logits, sp.top_k)
    if sp.top_p < 1.0:
        logits = _apply_top_p(logits, sp.top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
