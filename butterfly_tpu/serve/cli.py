"""`butterfly` CLI: the reference's planned client-facing entrypoints
(/root/reference/CLAUDE.md:23; BASELINE.json north_star names
`butterfly serve` / `generate`).

    butterfly generate --model gpt2-124m --prompt "hello" --max-new 32
    butterfly serve    --model llama3-8b --port 8000
    butterfly bench    --model tiny [--serving --mixed]
    butterfly route    --backends 10.0.0.1:8000,10.0.0.2:8000
    butterfly workload generate|replay|sweep   (workload subsystem)
    butterfly lint     [paths...]   (project-native static analysis)

Models load from --ckpt (HF safetensors dir or our sharded checkpoint);
without --ckpt, weights are random-initialized (smoke/demo mode).
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="butterfly",
                                description="Butterfly-TPU inference CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("--model", default="tiny",
                        help="preset name (gpt2-124m, llama3-8b, llama3-70b, "
                             "mixtral-8x7b) or 'tiny'")
        sp.add_argument("--ckpt", default=None, help="checkpoint path")
        sp.add_argument("--tokenizer", default=None)
        sp.add_argument("--dtype", default=None, help="override compute dtype")
        sp.add_argument("--tensor-parallel", type=int, default=1)
        sp.add_argument("--stage-parallel", type=int, default=1)
        sp.add_argument("--expert-parallel", type=int, default=1)
        sp.add_argument("--data-parallel", type=int, default=1)
        sp.add_argument("--seq-parallel", type=int, default=1,
                        help="sequence/context parallelism: shard the "
                             "prompt over N devices (the long-context "
                             "path — prefix KV stays sharded where it "
                             "was computed)")
        sp.add_argument("--seq-impl", choices=["ring", "ulysses"],
                        default="ring",
                        help="sequence-parallel attention: 'ring' "
                             "(ppermute K/V rotation, no head-count "
                             "constraint) or 'ulysses' (all_to_all "
                             "head<->sequence reshard; needs heads "
                             "divisible by / replicable over the axis)")
        sp.add_argument("--max-seq", type=int, default=2048)
        sp.add_argument("--dcn-axes", default="data",
                        help="comma list of mesh axes to place ACROSS TPU "
                             "slices (DCN) on multi-slice jobs; all other "
                             "axes stay within a slice on ICI "
                             "(e.g. 'data' or 'data,stage')")
        sp.add_argument("--quant", choices=["none", "int8"], default="none",
                        help="weight-only quantization (int8 halves the "
                             "HBM bytes the decode loop streams)")

    def kv_quant_flag(sp):
        sp.add_argument("--kv-quant", choices=["none", "int8"],
                        default="none",
                        help="KV-cache quantization (int8 halves the cache "
                             "bytes — the dominant decode-loop term at "
                             "serving batch sizes; applies to both the "
                             "contiguous and the paged serving cache)")

    g = sub.add_parser("generate", help="one-shot text generation")
    common(g)
    kv_quant_flag(g)
    g.add_argument("--prompt", default="Hello")
    g.add_argument("--max-new", type=int, default=64)
    g.add_argument("--temperature", type=float, default=0.0)
    g.add_argument("--top-k", type=int, default=0)
    g.add_argument("--top-p", type=float, default=1.0)
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--speculate", type=int, default=0, metavar="GAMMA",
                   help="prompt-lookup speculative decoding: draft GAMMA "
                        "tokens per step, verify in one forward. Greedy "
                        "output is identical to plain decode; with "
                        "--temperature > 0 the rejection-sampling "
                        "correction keeps the output distribution exact")

    s = sub.add_parser("serve", help="HTTP serving with continuous batching")
    common(s)
    kv_quant_flag(s)
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--max-batch", type=int, default=8)
    s.add_argument("--page-size", type=int, default=16)
    s.add_argument("--top-k", type=int, default=0,
                   help="serving-wide top-k sampling filter")
    s.add_argument("--top-p", type=float, default=1.0)
    s.add_argument("--max-queue", type=int, default=256)
    s.add_argument("--no-trace", action="store_true",
                   help="disable per-request tracing (GET /debug/requests "
                        "then reports enabled=false); tracing is on by "
                        "default and costs one ring-buffer append per "
                        "scheduling event")
    s.add_argument("--role", choices=["prefill", "decode", "both"],
                   default="both",
                   help="fleet placement role advertised on /health: the "
                        "disaggregated control plane (`butterfly route "
                        "--disaggregate`) sends prefill-heavy requests to "
                        "'prefill' replicas and generation to 'decode' "
                        "ones. Advisory — the replica serves whatever it "
                        "is sent; 'both' (default) joins both tiers")
    s.add_argument("--prefix-caching", action="store_true",
                   help="reuse KV pages across requests sharing a prompt "
                        "prefix (content-hashed, refcounted; cuts TTFT for "
                        "shared system prompts)")
    s.add_argument("--host-tier-mb", type=float, default=0.0,
                   help="host-RAM KV tier budget in MiB (requires "
                        "--prefix-caching): device-pool evictions demote "
                        "pages to host memory instead of dropping them, "
                        "and a later prefix hit on an evicted chain "
                        "revives the pages back to device — TTFT of a "
                        "warm hit at host-RAM prices. 0 (default) = off")
    s.add_argument("--host-tier-dir", default=None, metavar="DIR",
                   help="optional disk-spill directory for the host KV "
                        "tier: pages LRU-demoted past --host-tier-mb "
                        "spill to .npz files here instead of being "
                        "dropped (a third tier below host RAM)")
    s.add_argument("--speculate", type=int, default=0, metavar="GAMMA",
                   help="serving-path speculative decoding on the block "
                        "pipeline: draft GAMMA tokens per slot from the "
                        "device-side token history, verify ALL slots in "
                        "one batched (GAMMA+1)-token forward per round, "
                        "accept/rollback on device. Sampling-safe "
                        "(rejection-sampling correction keeps "
                        "temperature/top-k/top-p requests exact); "
                        "clients opt out per request with "
                        '"speculative": false')
    s.add_argument("--draft-source", default="ngram",
                   help="spec-block draft source (RuntimeConfig."
                        "draft_model): 'ngram' = prompt lookup over the "
                        "device-side history (free, earns ~0 on "
                        "non-repetitive traffic); 'model' = a real "
                        "on-device draft model (models/draft.py) whose "
                        "per-round forward runs inside the jitted spec "
                        "scan over its own rollback-exact KV cache; "
                        "custom sources register via "
                        "engine.serving.register_draft_source")
    s.add_argument("--draft-layers", type=int, default=0,
                   help="--draft-source model: derive the draft from "
                        "the first N layers of the TARGET checkpoint "
                        "(embed/unembed shared on-chip, zero extra HBM "
                        "for them). 0 = auto (num_layers/4, floor 1); "
                        "ignored with --draft-ckpt")
    s.add_argument("--draft-ckpt", default=None,
                   help="--draft-source model: load an independent "
                        "narrow HF-format draft checkpoint (same "
                        "vocabulary as the target — validated) instead "
                        "of deriving by truncation")
    s.add_argument("--spec-tree", type=int, default=0, metavar="WIDTH",
                   help="token-TREE speculation (SpecInfer-style, "
                        "ISSUE 19): each draft expansion step branches "
                        "the top-WIDTH children and one forward "
                        "verifies the whole tree under a tree-attention "
                        "mask, so sibling branches hedge the draft's "
                        "uncertainty at the same verify FLOPs. "
                        "Requires --draft-source model; greedy output "
                        "stays byte-identical to plain decode and "
                        "sampled output stays distribution-exact "
                        "(recursive-residual acceptance). 0/1 = linear "
                        "chain (default)")
    s.add_argument("--spec-tree-nodes", type=int, default=0, metavar="N",
                   help="total tree node budget per verify, INCLUDING "
                        "the root chain token; (N-1) must divide by "
                        "--spec-tree. 0 = auto GAMMA+1, which holds "
                        "verify FLOPs equal to the linear chain")
    def positive_int(v):
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
        return n

    s.add_argument("--decode-steps-per-tick", type=positive_int, default=1,
                   help="fused block width: this many decode iterations "
                        "(or, with --speculate, draft+verify+accept "
                        "rounds) run per scheduler tick inside ONE "
                        "jitted scan (on-device sampling, RNG, and EOS "
                        "masking), drained in ONE stacked fetch. Raise "
                        "to amortize per-token host overhead (tokens "
                        "then surface in bursts)")
    s.add_argument("--prefill-max-batch", type=positive_int, default=8,
                   help="max waiting requests gang-admitted into ONE "
                        "batched [B, Tbucket] prefill dispatch per "
                        "scheduler tick (group admission). A burst of "
                        "arrivals prefills as a group under the "
                        "prefill-chunk token budget instead of one "
                        "prompt per tick — the TTFT lever under bursty "
                        "load. B buckets to powers of two clamped "
                        "here, so raising it adds at most one compiled "
                        "program per prompt-length bucket")
    s.add_argument("--seq-parallel-threshold", type=int, default=0,
                   help="long-context admission lane: prompts LONGER "
                        "than this many tokens prefill through chunked "
                        "seq-parallel dispatches sharded over the "
                        "mesh's seq axis (requires --seq-parallel > 1), "
                        "landing their KV in the ordinary paged pool — "
                        "prefix-cache-visible and decoded like any "
                        "other slot. 0 (default) = off")
    s.add_argument("--seq-parallel-chunk", type=int, default=0,
                   help="tokens per seq-parallel prefill dispatch "
                        "(rounded up to a multiple of the seq degree); "
                        "0 = auto: seq degree x prefill_chunk, so the "
                        "per-device chunk share matches the ordinary "
                        "prefill budget and decode ITL interference "
                        "stays within the same bound")
    def slo_flags(sp):
        sp.add_argument("--slo-ttft-ms", type=float, default=None,
                        help="declared time-to-first-token objective in "
                             "milliseconds: per-request attainment is "
                             "recorded into the slo_ttft_ok_total / "
                             "slo_violations_total{kind} counters and "
                             "the rolling slo_burn_rate gauge (unset = "
                             "no SLO accounting)")
        sp.add_argument("--slo-itl-ms", type=float, default=None,
                        help="declared mean inter-token-latency "
                             "objective in milliseconds (per finished "
                             "request, the streaming rate a client "
                             "experiences); recorded like --slo-ttft-ms")

    slo_flags(s)
    s.add_argument("--profiler-port", type=int, default=0,
                   help="start the on-demand XProf profiler server on "
                        "this port (0 = off): TensorBoard/XProf can "
                        "then trigger captures of the live replica. "
                        "ImportError/port-in-use degrade to a logged "
                        "warning, never a crash. POST /debug/profile "
                        "{duration_ms} captures a duration-bounded "
                        "trace of the live tick loop either way")
    s.add_argument("--flightrec-dir", default=None, metavar="DIR",
                   help="write anomaly flight-recorder post-mortem "
                        "artifacts (JSON) here when a trigger fires "
                        "(SLO burn, preemption storm, deadline-expiry "
                        "burst, wedge latch); unset keeps them "
                        "in-memory at GET /debug/flightrecorder only")
    s.add_argument("--inflight-blocks", type=positive_int, default=2,
                   help="decode blocks kept in flight on the device "
                        "(dispatch-ahead): block t+1 chains on block "
                        "t's device-resident carry before t is "
                        "drained, so host scheduling overlaps device "
                        "compute. 1 = the synchronous drain-every-tick "
                        "loop; the device_bubble_seconds histogram "
                        "shows whether the depth is enough to keep the "
                        "device busy through a tick's host section")
    s.add_argument("--timeseries-interval", type=float, default=1.0,
                   metavar="SECONDS",
                   help="periodic signal-history sampling interval for "
                        "GET /debug/timeseries (the bounded ring "
                        "tools/dashboard.py renders; alert rules note "
                        "threshold crossings into the flight "
                        "recorder). 0 disables the recorder entirely "
                        "(zero extra per-tick host work)")

    b = sub.add_parser("bench", help="throughput microbenchmark")
    common(b)
    kv_quant_flag(b)
    b.add_argument("--batch", type=int, default=8)
    b.add_argument("--prompt-len", type=int, default=128)
    b.add_argument("--max-new", type=int, default=128)
    b.add_argument("--serving", action="store_true",
                   help="also run the PRODUCT serving-path benchmark "
                        "(Scheduler + ServingEngine under staggered "
                        "arrivals) at this operating point and merge "
                        "its serving_* keys into the JSON line")
    b.add_argument("--inflight-blocks", type=positive_int, default=2,
                   help="dispatch-ahead depth for --serving (see "
                        "`serve --inflight-blocks`); the serving JSON "
                        "carries device_bubble_p50/p95 so the overlap "
                        "is measurable at this depth")
    b.add_argument("--max-batch", type=positive_int, default=0,
                   help="serving slot count for --serving/--mixed "
                        "(default: --batch) — decouples the serving "
                        "operating point from the isolated-decode "
                        "batch, so e.g. the ROADMAP item 1 batch-128 "
                        "serving run is `--serving --max-batch 128` "
                        "without re-timing isolated decode at 128")
    b.add_argument("--mixed", action="store_true",
                   help="also run the mixed-workload serving phase "
                        "(ISSUE 10): the canned mixed_chat population "
                        "fired open-loop in bursts against an under-"
                        "provisioned page pool — preemption, shedding, "
                        "and deadline scrubbing measured instead of "
                        "idle — plus the decode_steps_per_tick x "
                        "inflight_blocks operating-point table + knee; "
                        "merges mixed_* keys into the JSON line")
    b.add_argument("--host-tier-mb", type=float, default=0.0,
                   help="with --mixed: give the engine a host-RAM KV "
                        "tier of this many MiB so the contested pool "
                        "demotes/revives instead of dropping — merges "
                        "kv_tier_hit_rate and kv_tier_restore_seconds_"
                        "p50/p95 into the JSON line")

    # multi-replica router: fronts N `butterfly serve` replicas with
    # prefix-affinity routing + health-aware failover (router/). Loads no
    # model and touches no accelerator — deliberately NOT given the
    # common() model/mesh flags.
    r = sub.add_parser("route",
                       help="route requests across serve replicas "
                            "(prefix-affinity + health-aware failover)")
    r.add_argument("--backends", required=True,
                   help="comma-separated replica addresses, e.g. "
                        "10.0.0.1:8000,10.0.0.2:8000")
    r.add_argument("--port", type=int, default=8100)
    r.add_argument("--host", default="0.0.0.0")
    r.add_argument("--page-size", type=int, default=16,
                   help="MUST match the replicas' --page-size: affinity "
                        "keys hash the same token blocks their prefix "
                        "caches key pages by")
    r.add_argument("--affinity-blocks", type=int, default=4,
                   help="leading full prompt blocks hashed into the "
                        "affinity key (requests agreeing on this many "
                        "blocks share a replica)")
    r.add_argument("--saturate-after", type=int, default=8,
                   help="outstanding requests at which the affinity "
                        "target is considered saturated and routing "
                        "falls back to least-outstanding")
    r.add_argument("--probe-interval", type=float, default=0.5,
                   help="seconds between /health probes of each replica")
    r.add_argument("--dead-after", type=int, default=3,
                   help="consecutive connect failures before a replica "
                        "is marked dead (re-probed with jittered "
                        "exponential backoff)")
    r.add_argument("--read-timeout", type=float, default=300.0,
                   help="per-request socket timeout toward a replica")
    r.add_argument("--disaggregate", action="store_true",
                   help="run the KV-aware fleet control plane instead of "
                        "the plain router: prefill-heavy requests go to "
                        "--role prefill replicas, their KV pages stream "
                        "to a --role decode replica by chain hash "
                        "(GET /kv/pages -> POST /kv/import), and "
                        "generation finishes there; GET /fleet/state "
                        "exposes the placement table")
    r.add_argument("--disagg-threshold", type=int, default=64,
                   help="predicted fresh-prefill tokens at which a "
                        "request is worth the prefill/decode handoff "
                        "(below it, requests dispatch directly to the "
                        "decode tier)")
    slo_flags(r)  # control-plane SLO accounting for disaggregated
    # requests (fleet_slo_* counters + burn rate; measured across the
    # whole handoff, the latency the CLIENT experiences)

    # local disaggregated fleet for manual debugging: N prefill + M
    # decode in-process replicas behind one control plane, all tiny-
    # model loopback — the same harness the fleet soak tests drive.
    f = sub.add_parser("fleet",
                       help="spin a local prefill/decode fleet (replicas "
                            "+ control plane, in-process) for manual "
                            "debugging")
    f.add_argument("--topology", default="2p2d",
                   help="'<N>p<M>d' = N prefill + M decode replicas "
                        "(default 2p2d), or a bare count for a "
                        "role-less pool")
    f.add_argument("--page-size", type=int, default=8)
    f.add_argument("--max-batch", type=int, default=2)
    f.add_argument("--max-seq", type=int, default=128)
    f.add_argument("--disagg-threshold", type=int, default=16)
    f.add_argument("--autoscale", action="store_true",
                   help="run the closed-loop autoscaler (fleet/"
                        "autoscale.py) on every tier in the topology: "
                        "scraped queue-depth ring history grows a "
                        "saturated tier (warm-before-join) and shrinks "
                        "an idle one (drain-before-retire), "
                        "independently per tier; decisions land in "
                        "GET /debug/flightrecorder")
    f.add_argument("--scale-min", type=int, default=1,
                   help="autoscaler floor per tier (default 1)")
    f.add_argument("--scale-max", type=int, default=4,
                   help="autoscaler ceiling per tier (default 4)")
    f.add_argument("--scale-high", type=float, default=4.0,
                   help="tier-mean queue_depth above which a tier "
                        "grows (default 4.0)")
    f.add_argument("--scale-low", type=float, default=0.5,
                   help="tier-mean queue_depth below which a tier "
                        "shrinks, after the hysteresis cooldown "
                        "(default 0.5)")
    f.add_argument("--host-tier-mb", type=float, default=0.0,
                   help="per-replica host-RAM KV tier budget in MiB "
                        "(see `serve --host-tier-mb`); 0 = off")
    f.add_argument("--host-tier-dir", default=None, metavar="DIR",
                   help="disk-spill directory for the replicas' host "
                        "KV tiers (see `serve --host-tier-dir`)")
    f.add_argument("--chaos", default=None, metavar="PLAN",
                   help="seeded fault-injection plan: a JSON file "
                        '({"seed": N, "faults": [{"kind": "delay|error|'
                        'wedge|drop|truncate|slow_stream", "target": '
                        '"prefill|decode:0|*", "endpoint": "/generate", '
                        '"p": 0.3, "count": 5}, ...]}) or the literal '
                        "'default' for the stock soak plan "
                        "(fleet/chaos.py). Faults inject at the replica "
                        "HTTP fronts and the control plane's handoff "
                        "legs, deterministically per seed")
    slo_flags(f)  # declared objectives activate SLO accounting AND
    # SLO-aware admission shedding on every in-process replica

    # workload subsystem (butterfly_tpu/workload/): generate seeded
    # stochastic traffic traces, replay them open-loop at a live URL,
    # and sweep scheduler operating points — the measurement substrate
    # the mixed bench phase runs on.
    w = sub.add_parser("workload",
                       help="stochastic workload tooling: generate a "
                            "seeded trace, replay one at a server "
                            "open-loop, or sweep scheduler operating "
                            "points")
    wsub = w.add_subparsers(dest="wcmd", required=True)

    def workload_shape_flags(sp, for_generate=True):
        if for_generate:
            sp.add_argument("--workload", default="mixed_chat",
                            help="canned workload name "
                                 "(mixed_chat, uniform)")
            sp.add_argument("--n", type=int, default=32,
                            help="requests to sample")
            sp.add_argument("--seed", type=int, default=0)
            sp.add_argument("--arrival", default="poisson:8",
                            help="arrival process: poisson:<rate>, "
                                 "burst:<rate_on>:<mean_on_s>:"
                                 "<mean_off_s>[:<rate_off>], "
                                 "ramp:<r0>:<r1>:<ramp_s>")
            sp.add_argument("--vocab", type=int, default=258,
                            help="token-id vocabulary (match the "
                                 "target model; 258 = tiny)")
            sp.add_argument("--page-size", type=int, default=16,
                            help="prefix alignment unit — match the "
                                 "server's --page-size")
            sp.add_argument("--prompt-lo", type=int, default=32)
            sp.add_argument("--prompt-hi", type=int, default=1024)
            sp.add_argument("--max-new-lo", type=int, default=8)
            sp.add_argument("--max-new-hi", type=int, default=256)
            sp.add_argument("--deadline-ms", type=float, default=None,
                            help="latency budget for the workload's "
                                 "deadline-carrying cohort")

    wg = wsub.add_parser("generate",
                         help="sample a workload + arrival schedule "
                              "into a JSONL trace")
    workload_shape_flags(wg)
    wg.add_argument("--out", required=True, metavar="FILE",
                    help="trace output path (JSONL)")

    wr = wsub.add_parser("replay",
                         help="fire a saved trace at a live server/"
                              "router URL with absolute-time fidelity "
                              "(open loop)")
    wr.add_argument("--trace", required=True, metavar="FILE")
    wr.add_argument("--url", required=True,
                    help="target base URL, e.g. http://127.0.0.1:8000")
    wr.add_argument("--speed", type=float, default=1.0,
                    help="schedule compression: 2.0 replays twice as "
                         "fast")
    wr.add_argument("--timeout", type=float, default=120.0)
    wr.add_argument("--slo-ttft-ms", type=float, default=None)
    wr.add_argument("--slo-itl-ms", type=float, default=None)

    ws = wsub.add_parser("sweep",
                         help="run one workload across a "
                              "decode_steps_per_tick x inflight_blocks "
                              "grid (in-process engine) and emit the "
                              "latency/throughput table + knee")
    workload_shape_flags(ws)
    ws.add_argument("--model", default="tiny")
    ws.add_argument("--quant", choices=["none", "int8"], default="none")
    kv_quant_flag(ws)
    ws.add_argument("--ckpt", default=None)
    ws.add_argument("--grid", default="1,4x1,2",
                    help="'<k1>,<k2>x<d1>,<d2>' decode_steps_per_tick "
                         "x inflight_blocks values, full cross product")
    ws.add_argument("--max-batch", type=int, default=8)
    ws.add_argument("--num-pages", type=int, default=0,
                    help="KV page pool size (0 = full provisioning; "
                         "set below max_batch x pages-per-seq to "
                         "measure preemption behavior)")
    ws.add_argument("--slo-ttft-ms", type=float, default=None,
                    help="arm SLO-aware admission shedding during the "
                         "sweep (sheds are counted per point)")

    # project-native static analysis (tools/staticcheck.py, ISSUE 11):
    # the donation/lock/host-sync/determinism contracts as AST rules —
    # the same walk the tier-1 test and bench.py's preflight run.
    li = sub.add_parser("lint",
                        help="AST lint for the serving contracts "
                             "(donation, locks, host-sync, HTTP "
                             "timeouts, determinism, PRNG hygiene); "
                             "exit 1 on any unsuppressed finding")
    li.add_argument("paths", nargs="*",
                    help="files/trees to lint (default: butterfly_tpu "
                         "tools tests, fixture snippets excluded)")
    li.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog (id, slug, scope, "
                         "invariant) and exit")
    li.add_argument("--json", action="store_true",
                    help="machine-readable jsonl findings")
    li.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    li.add_argument("--force", action="store_true",
                    help="ignore per-rule scopes (ad-hoc sweeps)")

    # timeseries dashboard renderer (tools/dashboard.py, ISSUE 16):
    # stdlib-only like `lint` — loads no model, touches no accelerator.
    d = sub.add_parser("dash",
                       help="render a dumped /debug/timeseries or "
                            "/fleet/timeseries body as a static HTML "
                            "dashboard (SVG sparklines, alert "
                            "annotations) or --text sparklines")
    d.add_argument("dump", help="JSON file (the timeseries body)")
    d.add_argument("--out", default=None,
                   help="write HTML here (default: stdout)")
    d.add_argument("--text", action="store_true",
                   help="unicode sparklines for terminals instead of "
                        "HTML")
    return p


def resolve_model(args):
    from butterfly_tpu.core.config import PRESETS, tiny
    from butterfly_tpu.models.common import Model
    if args.model == "tiny":
        cfg = tiny("llama", dtype="float32", param_dtype="float32")
    else:
        cfg = PRESETS[args.model]()
    if args.dtype:
        cfg = cfg.replace(dtype=args.dtype)
    if getattr(args, "expert_parallel", 1) > 1 and cfg.is_moe:
        # EP means GShard all_to_all dispatch, not an expert-sharded
        # dense MoE where every expert still computes every token.
        cfg = cfg.replace(moe_impl="ep")
    return Model(cfg)


def load_params(model, args):
    """Load (or random-init) weights; apply --quant before any sharding."""
    import jax
    if args.ckpt:
        from butterfly_tpu.ckpt import load_checkpoint
        params = load_checkpoint(args.ckpt, model.cfg)
    else:
        # btf: disable=BTF006 demo mode: no-ckpt random-init weights are deliberately identical across runs
        params = model.init(jax.random.PRNGKey(0))
    if getattr(args, "quant", "none") == "int8":
        from butterfly_tpu.quant import quantize_int8
        params = quantize_int8(params, model.cfg)
    return params


def build_mesh(args):
    """Mesh from the CLI parallelism flags; None when all are 1.

    Multi-host: call with BUTTERFLY_NUM_PROCESSES set and the coordinator
    flags in the environment — init_distributed runs first so
    jax.devices() spans every host (core/mesh.py).
    """
    import jax
    from butterfly_tpu.core.config import MeshConfig
    from butterfly_tpu.core.mesh import init_distributed, make_hybrid_mesh

    tp = getattr(args, "tensor_parallel", 1)
    pp = getattr(args, "stage_parallel", 1)
    ep = getattr(args, "expert_parallel", 1)
    dp = getattr(args, "data_parallel", 1)
    sq = getattr(args, "seq_parallel", 1)
    n = tp * pp * ep * dp * sq
    if n == 1:
        return None
    init_distributed()
    ndev = len(jax.devices())
    if n > ndev:
        raise SystemExit(
            f"error: --tensor-parallel {tp} x --stage-parallel {pp} x "
            f"--expert-parallel {ep} x --data-parallel {dp} x "
            f"--seq-parallel {sq} = {n} devices, "
            f"but only {ndev} are available")
    cfg = MeshConfig(data=dp, stage=pp, expert=ep, seq=sq, tensor=tp)
    # hybrid: on a multi-slice job the --dcn-axes span slices over DCN
    # and every per-layer collective stays on ICI; single-slice device
    # sets (and CPU) fall back to the plain mesh inside
    dcn = tuple(a for a in getattr(args, "dcn_axes", "data").split(",") if a)
    try:
        return make_hybrid_mesh(cfg, jax.devices()[:n], dcn_axes=dcn)
    except ValueError as e:
        raise SystemExit(f"error: {e}")


def shard_for_mesh(params, cfg, mesh):
    if mesh is None:
        return params
    from butterfly_tpu.quant import shard_quantized_params, tree_is_quantized
    if tree_is_quantized(params):
        return shard_quantized_params(params, cfg, mesh)
    from butterfly_tpu.parallel.partition import shard_params
    return shard_params(params, cfg, mesh)


def cmd_generate(args) -> int:
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine import InferenceEngine, SamplingParams
    from butterfly_tpu.utils.tokenizer import load_tokenizer

    model = resolve_model(args)
    tok = load_tokenizer(args.tokenizer or args.ckpt)
    mesh = build_mesh(args)
    params = shard_for_mesh(load_params(model, args), model.cfg, mesh)
    engine = InferenceEngine(
        model, params,
        runtime=RuntimeConfig(max_seq_len=args.max_seq,
                              kv_quant=args.kv_quant),
        mesh=mesh)
    vocab = model.cfg.vocab_size
    stop = tok.eos_id if tok.eos_id is not None and tok.eos_id < vocab else -1
    sp = SamplingParams(temperature=args.temperature, top_k=args.top_k,
                        top_p=args.top_p, max_new_tokens=args.max_new,
                        stop_token=stop)
    ids = tok.encode(args.prompt)
    bad = [i for i in ids if i >= vocab]
    if bad:
        print(f"error: tokenizer produced ids {bad[:5]} outside the model's "
              f"vocab ({vocab}); pass a matching --tokenizer", file=sys.stderr)
        return 2
    t0 = time.perf_counter()
    if args.seq_parallel > 1:
        if args.speculate > 0:
            print("error: --speculate does not compose with "
                  "--seq-parallel (the long-context path has no warm "
                  "multi-token verify)", file=sys.stderr)
            return 2
        # long-context path: sp_forward prefill + sp_decode_step loop
        # (engine.generate_long docs); --kv-quant int8 composes — the
        # seq-parallel cache shards int8 codes + scales and the ring
        # kernel dequantizes per block
        res = engine.generate_long(ids, sp, seed=args.seed,
                                   impl=args.seq_impl)
        dt = time.perf_counter() - t0
        n = int(res.lengths[0])
        print(tok.decode(res.tokens[0, :n].tolist()))
        print(f"[butterfly] {n} tokens in {dt:.2f}s over "
              f"{args.seq_parallel}-way sequence parallelism", file=sys.stderr)
        return 0
    if args.speculate > 0:
        try:
            res = engine.generate_speculative(ids, sp, gamma=args.speculate,
                                              seed=args.seed)
        except NotImplementedError as e:  # e.g. data/stage-parallel mesh
            print(f"error: {e}", file=sys.stderr)
            return 2
        dt = time.perf_counter() - t0
        n = len(res.tokens)
        text = tok.decode(res.tokens.tolist())
        print(text)
        print(f"[butterfly] {n} tokens in {dt:.2f}s via {res.forwards} "
              f"forwards ({res.tokens_per_forward:.2f} tok/forward, "
              f"{res.accepted_drafts} drafts accepted)", file=sys.stderr)
        return 0
    res = engine.generate([ids], sp, seed=args.seed)
    dt = time.perf_counter() - t0
    n = int(res.lengths[0])
    text = tok.decode(res.tokens[0, :n].tolist())
    print(text)
    print(f"[butterfly] {n} tokens in {dt:.2f}s "
          f"({n / dt:.1f} tok/s incl. compile)", file=sys.stderr)
    return 0


def cmd_serve(args) -> int:
    if getattr(args, "seq_parallel_threshold", 0) > 0 \
            and args.seq_parallel <= 1:
        print("error: --seq-parallel-threshold needs a seq axis — pass "
              "--seq-parallel N (> 1) to shard long prompts over N "
              "devices", file=sys.stderr)
        return 2
    from butterfly_tpu.serve.server import run_server
    return run_server(args)


def cmd_bench(args) -> int:
    from butterfly_tpu.obs.benchmark import (run_decode_benchmark,
                                             run_serving_benchmark)

    model = resolve_model(args)
    mesh = build_mesh(args)
    params = shard_for_mesh(load_params(model, args), model.cfg, mesh)
    stats = run_decode_benchmark(model, params, batch=args.batch,
                                 prompt_len=args.prompt_len,
                                 max_new=args.max_new, mesh=mesh,
                                 kv_quant=args.kv_quant)
    serving_batch = args.max_batch or args.batch
    if args.serving:
        # the serving path is single-engine: a mesh-sharded tree would
        # need the serving mesh wiring (ServingEngine(mesh=...)); keep
        # the CLI smoke single-chip like bench.py's driver
        serving = run_serving_benchmark(
            model, params, n_requests=2 * serving_batch,
            prompt_len=args.prompt_len, max_new=args.max_new,
            max_batch=serving_batch, kv_quant=args.kv_quant,
            inflight_blocks=args.inflight_blocks,
            isolated_decode_tok_s_chip=stats[
                "decode_tokens_per_sec_per_chip"])
        stats.update(serving)
        if mesh is None:
            # long-context row (ISSUE 20): builds its own seq=4 mesh
            # when the device count allows; on fewer devices it reports
            # longctx_supported: false plus the ring microbench pair
            from butterfly_tpu.obs.benchmark import run_longctx_benchmark
            stats.update(run_longctx_benchmark(
                model, params, kv_quant=args.kv_quant))
    if getattr(args, "mixed", False):
        # mixed-workload phase (ISSUE 10): mixed_chat open-loop bursts
        # against an under-provisioned pool + the operating-point sweep
        # (single-engine, like --serving)
        from butterfly_tpu.obs.benchmark import run_mixed_benchmark
        stats.update(run_mixed_benchmark(
            model, params, n_requests=2 * serving_batch,
            max_batch=serving_batch,
            prompt_lo=max(8, args.prompt_len // 4),
            prompt_hi=args.prompt_len,
            max_new_lo=max(4, args.max_new // 4),
            max_new_hi=args.max_new,
            inflight_blocks=args.inflight_blocks,
            host_kv_tier_mb=getattr(args, "host_tier_mb", 0.0),
            kv_quant=args.kv_quant))
    print(json.dumps({"metric": "decode_tokens_per_sec_per_chip",
                      "value": stats["decode_tokens_per_sec_per_chip"],
                      "unit": "tokens/sec/chip", **stats}))
    return 0


def cmd_route(args) -> int:
    backends = [b for b in args.backends.split(",") if b.strip()]
    if args.disaggregate:
        from butterfly_tpu.fleet.controlplane import fleet_forever
        return fleet_forever(backends, host=args.host, port=args.port,
                             page_size=args.page_size,
                             affinity_blocks=args.affinity_blocks,
                             saturate_after=args.saturate_after,
                             probe_interval=args.probe_interval,
                             dead_after=args.dead_after,
                             read_timeout=args.read_timeout,
                             disagg_threshold=args.disagg_threshold,
                             slo_ttft_s=(args.slo_ttft_ms / 1e3
                                         if args.slo_ttft_ms else None),
                             slo_itl_s=(args.slo_itl_ms / 1e3
                                        if args.slo_itl_ms else None))
    if args.slo_ttft_ms or args.slo_itl_ms:
        print("[butterfly] note: --slo-ttft-ms/--slo-itl-ms apply to "
              "the control plane (--disaggregate) and to the replicas' "
              "own `serve` flags; the plain router records no SLO",
              file=sys.stderr)
    from butterfly_tpu.router.proxy import route_forever
    return route_forever(backends, host=args.host, port=args.port,
                         page_size=args.page_size,
                         affinity_blocks=args.affinity_blocks,
                         saturate_after=args.saturate_after,
                         probe_interval=args.probe_interval,
                         dead_after=args.dead_after,
                         read_timeout=args.read_timeout)


def cmd_fleet(args) -> int:
    """`butterfly fleet`: the in-process soak topology, held open for
    manual poking (curl the printed control-plane URL)."""
    from butterfly_tpu.fleet.harness import start_fleet

    chaos = None
    if getattr(args, "chaos", None):
        from butterfly_tpu.fleet.chaos import ChaosPlan, default_plan
        chaos = default_plan() if args.chaos == "default" \
            else ChaosPlan.from_file(args.chaos)
        print(f"[butterfly] chaos plan armed: {len(chaos.rules)} rules, "
              f"seed {chaos.seed}", flush=True)
    print(f"[butterfly] starting local fleet {args.topology} "
          f"(tiny model, warming each replica)...", flush=True)
    slo_ttft = getattr(args, "slo_ttft_ms", None)
    slo_itl = getattr(args, "slo_itl_ms", None)
    fleet = start_fleet(args.topology, page_size=args.page_size,
                        max_batch=args.max_batch, max_seq=args.max_seq,
                        disagg_threshold=args.disagg_threshold,
                        chaos=chaos,
                        host_kv_tier_mb=getattr(args, "host_tier_mb", 0.0),
                        host_kv_tier_dir=getattr(args, "host_tier_dir",
                                                 None),
                        slo_ttft_s=slo_ttft / 1e3 if slo_ttft else None,
                        slo_itl_s=slo_itl / 1e3 if slo_itl else None)
    scaler = None
    if getattr(args, "autoscale", False):
        from butterfly_tpu.fleet.autoscale import Autoscaler, TierPolicy
        from butterfly_tpu.fleet.harness import parse_topology
        policies = [TierPolicy(role, min_replicas=args.scale_min,
                               max_replicas=args.scale_max,
                               high=args.scale_high, low=args.scale_low)
                    for role in dict.fromkeys(parse_topology(args.topology))]
        scaler = Autoscaler(fleet.state, fleet.spawn, fleet.retire,
                            policies, interval_s=1.0)
        scaler.start()
        print(f"[butterfly] autoscaler live on "
              f"{[p.role for p in policies]} "
              f"(bounds {args.scale_min}..{args.scale_max}, band "
              f"{args.scale_low}..{args.scale_high}; decisions at "
              f"GET /debug/flightrecorder)", flush=True)
    print(f"[butterfly] control plane: {fleet.url}  "
          f"(GET /fleet/state, POST /generate)", flush=True)
    for r in fleet.replicas:
        print(f"[butterfly]   replica {r.rid}  role={r.role}", flush=True)
    print("[butterfly] Ctrl-C to stop", flush=True)
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        if scaler is not None:
            scaler.stop()
        fleet.stop()
    return 0


def cmd_workload(args) -> int:
    """`butterfly workload generate|replay|sweep` (ISSUE 10): the
    seeded traffic-modeling subsystem's CLI surface. generate/replay
    are stdlib-fast (no engine); sweep builds an in-process engine."""
    from butterfly_tpu.workload import (assign_arrivals, get_workload,
                                        parse_arrival)
    from butterfly_tpu.workload import replay as replay_mod

    if args.wcmd == "generate":
        wl = get_workload(args.workload, page_size=args.page_size,
                          vocab=args.vocab, prompt_lo=args.prompt_lo,
                          prompt_hi=args.prompt_hi,
                          max_new_lo=args.max_new_lo,
                          max_new_hi=args.max_new_hi,
                          deadline_ms=args.deadline_ms)
        specs = wl.sample(args.n, args.seed)
        assign_arrivals(specs, parse_arrival(args.arrival), args.seed)
        replay_mod.save_trace(args.out, specs, workload=wl,
                              arrival=args.arrival, seed=args.seed)
        cohorts = {}
        for s in specs:
            cohorts[s.cohort] = cohorts.get(s.cohort, 0) + 1
        print(json.dumps({
            "trace": str(args.out), "workload": wl.name, "n": len(specs),
            "seed": args.seed, "arrival": args.arrival,
            "cohorts": cohorts,
            "prompt_tokens": sum(len(s.tokens) for s in specs),
            "max_new_tokens": sum(s.max_new for s in specs),
            "span_s": round(specs[-1].arrival_s, 3) if specs else 0.0}))
        return 0
    if args.wcmd == "replay":
        _, specs = replay_mod.load_trace(args.trace)
        stats = replay_mod.replay_trace(
            args.url, specs, speed=args.speed, timeout=args.timeout,
            slo_ttft_ms=args.slo_ttft_ms, slo_itl_ms=args.slo_itl_ms)
        print(json.dumps(stats, indent=2))
        # like loadgen: sheds/504s are requested backpressure; only
        # transport errors / 5xx faults fail the replay
        return 0 if stats["outcomes"]["error"] == 0 else 1
    # sweep: in-process engine over the operating-point grid
    import jax
    from butterfly_tpu.core.config import PRESETS, tiny
    from butterfly_tpu.models.common import Model
    from butterfly_tpu.workload.sweep import (parse_grid,
                                              run_operating_point_sweep)
    cfg = tiny("llama", dtype="float32", param_dtype="float32") \
        if args.model == "tiny" else PRESETS[args.model]()
    model = Model(cfg)
    params = load_params(model, args)
    # the sweep drives a real engine, so the workload's vocabulary is
    # the MODEL's (the --vocab flag applies to `generate`, whose trace
    # may target any server)
    wl = get_workload(args.workload, page_size=args.page_size,
                      vocab=model.cfg.vocab_size,
                      prompt_lo=args.prompt_lo, prompt_hi=args.prompt_hi,
                      max_new_lo=args.max_new_lo,
                      max_new_hi=args.max_new_hi,
                      deadline_ms=args.deadline_ms)
    out = run_operating_point_sweep(
        model, params, workload=wl, arrival=args.arrival,
        n_requests=args.n, grid=parse_grid(args.grid),
        max_batch=args.max_batch, num_pages=args.num_pages,
        kv_quant=args.kv_quant, slo_ttft_ms=args.slo_ttft_ms,
        seed=args.seed)
    print(json.dumps(out, indent=2))
    return 0


def cmd_lint(args) -> int:
    """`butterfly lint`: the project-native static analyzer
    (tools/staticcheck.py) from the package entrypoint. The analyzer
    lives with the repo's tooling, not inside the wheel — a source
    checkout is where the contracts it enforces exist."""
    import importlib
    from pathlib import Path

    tools = Path(__file__).resolve().parent.parent.parent / "tools"
    if not (tools / "staticcheck.py").exists():
        print("error: butterfly lint needs the repo's tools/ directory "
              "(run from a source checkout)", file=sys.stderr)
        return 2
    sys.path.insert(0, str(tools))
    try:
        staticcheck = importlib.import_module("staticcheck")
    finally:
        sys.path.remove(str(tools))
    argv = list(args.paths)
    if args.list_rules:
        argv.append("--list-rules")
    if args.json:
        argv.append("--json")
    if args.show_suppressed:
        argv.append("--show-suppressed")
    if args.force:
        argv.append("--force")
    return staticcheck.main(argv)


def cmd_dash(args) -> int:
    """`butterfly dash`: the stdlib timeseries dashboard renderer
    (tools/dashboard.py) from the package entrypoint — same source-
    checkout contract as `butterfly lint`."""
    import importlib
    from pathlib import Path

    tools = Path(__file__).resolve().parent.parent.parent / "tools"
    if not (tools / "dashboard.py").exists():
        print("error: butterfly dash needs the repo's tools/ directory "
              "(run from a source checkout)", file=sys.stderr)
        return 2
    sys.path.insert(0, str(tools))
    try:
        dashboard = importlib.import_module("dashboard")
    finally:
        sys.path.remove(str(tools))
    argv = [args.dump]
    if args.out:
        argv += ["--out", args.out]
    if args.text:
        argv.append("--text")
    return dashboard.main(argv)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return {"generate": cmd_generate, "serve": cmd_serve,
            "bench": cmd_bench, "route": cmd_route,
            "fleet": cmd_fleet, "workload": cmd_workload,
            "lint": cmd_lint, "dash": cmd_dash}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
