"""HTTP serving endpoint — implemented with the continuous-batching
scheduler in slice 4 (SURVEY.md §7 build order step 4)."""
from __future__ import annotations


def run_server(args) -> int:
    raise NotImplementedError(
        "`butterfly serve` requires the continuous-batching scheduler "
        "(butterfly_tpu.sched), which lands in the next build slice. "
        "Use `butterfly generate` for one-shot inference meanwhile.")
