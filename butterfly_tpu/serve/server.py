"""HTTP serving: the reference's planned client-facing API layer
(/root/reference/CLAUDE.md:23) over the continuous-batching scheduler.

stdlib-only (ThreadingHTTPServer — no web framework dependencies, per the
zero-egress environment):

* POST /generate  {"prompt": str | "tokens": [int], "max_tokens",
                   "temperature", "stop_token", "stream": bool}
  -> {"text", "tokens", "ttft_s", "total_s"}; with "stream": true the
  response is SSE (`data: {"token": id, "text": piece}` per token,
  terminated by `data: [DONE]`).
* GET /metrics    Prometheus text (obs/metrics.py)
* GET /health     {"status": "ok"}

One scheduler thread owns all device work (ticks); HTTP handler threads
only enqueue requests and wait on per-request queues — JAX never runs on
more than one host thread.
"""
from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from butterfly_tpu.obs.metrics import ThroughputWindow, render_prometheus


class ServerState:
    def __init__(self, scheduler, tokenizer, max_queue: int = 256,
                 heartbeat=None):
        self.sched = scheduler
        self.tok = tokenizer
        self.lock = threading.Lock()       # guards scheduler state
        self.wake = threading.Event()      # new work signal
        self.stop = threading.Event()
        self.max_queue = max_queue
        self.throughput = ThroughputWindow()
        self.t_start = time.monotonic()
        self.error: str = ""               # set => serving is wedged: 503s
        self.thread = threading.Thread(target=self._loop, daemon=True)
        # Optional HeartbeatMonitor (obs/health.py): the scheduler
        # thread beats after every tick and runs the probe in-thread
        # when idle (JAX stays on ONE host thread); the monitor's
        # watchdog thread only watches wall-clock staleness, so a HUNG
        # tick latches too. On latch: wedge serving (503s) and drain
        # host-side only (abort_all never touches the dead device).
        self.heartbeat = heartbeat
        if heartbeat is not None:
            prev = heartbeat.on_failure
            if prev is None:
                heartbeat.on_failure = self._on_heartbeat_failure
            else:  # chain a caller-provided hook, don't discard it
                def chained(exc, _prev=prev):
                    self._on_heartbeat_failure(exc)
                    _prev(exc)
                heartbeat.on_failure = chained
            if not heartbeat._thread.is_alive():
                heartbeat.start()
            if not heartbeat.healthy:  # latched before we were handed it
                self._on_heartbeat_failure(None)

    def _on_heartbeat_failure(self, exc) -> None:
        # Runs on the watchdog thread: host-only bookkeeping, no JAX.
        # In the hung-tick scenario the scheduler thread HOLDS self.lock
        # (stuck inside a device call) — waiting would deadlock the
        # recovery. Try briefly; on timeout set the error ONLY: the
        # watchdog cannot distinguish hung from slow, and draining
        # concurrently with a slow-but-alive tick would corrupt
        # scheduler state. The scheduler loop drains itself at its next
        # iteration (error check in _loop); a truly hung tick never
        # reaches it, but then its host state is frozen and 503s flow.
        self.error = f"heartbeat failed: {self.heartbeat.last_error}"
        if self.lock.acquire(timeout=2.0):
            try:
                self.sched.abort_all()
            finally:
                self.lock.release()

    # -- scheduler thread ----------------------------------------------------

    def _loop(self) -> None:
        while not self.stop.is_set():
            if self.error:
                # wedged (in-tick exception, or the watchdog latched
                # while we were mid-tick): drain remaining work under
                # the lock — the single host-only drain path — and
                # idle. Beat the heartbeat: this loop is alive and
                # wedged-by-design; re-latching on staleness would
                # clobber the real root cause in self.error.
                with self.lock:
                    if self.sched.has_work:
                        self.sched.abort_all()
                if self.heartbeat is not None:
                    self.heartbeat.beat()
                self.wake.wait(timeout=0.2)
                self.wake.clear()
                continue
            try:
                with self.lock:
                    has_work = self.sched.has_work
                    made = self.sched.tick() if has_work else 0
            except Exception as e:  # device/OOM errors must not wedge:
                # set the error; the wedged branch above drains on the
                # next iteration (one drain path, not two)
                self.error = f"{type(e).__name__}: {e}"
                continue
            if has_work:
                if made:
                    self.throughput.record(made)
                if self.heartbeat is not None:
                    self.heartbeat.beat()  # a completed tick IS liveness
            else:
                if self.heartbeat is not None:
                    self.heartbeat.maybe_probe()  # idle: probe in-thread
                self.wake.wait(timeout=0.05)
                self.wake.clear()

    # -- handler-thread API ---------------------------------------------------

    def submit(self, tokens, max_tokens, temperature, stop_token):
        q: queue.Queue = queue.Queue()

        def on_token(req, token):
            q.put(token)

        def on_finish(req):
            q.put(None)  # completion sentinel (after the last on_token)

        with self.lock:
            # re-check under the lock: the heartbeat may have wedged the
            # server between the handler's check and this admission
            if self.error:
                raise RuntimeError("server wedged: " + self.error)
            if len(self.sched.waiting) >= self.max_queue:
                return None, None
            req = self.sched.submit(tokens, max_new_tokens=max_tokens,
                                    temperature=temperature,
                                    stop_token=stop_token,
                                    on_token=on_token, on_finish=on_finish)
        self.wake.set()
        return req, q

    def metrics_text(self) -> str:
        with self.lock:
            vals = self.sched.metrics()
        vals["tokens_per_sec"] = self.throughput.rate()
        vals["uptime_seconds"] = time.monotonic() - self.t_start
        return render_prometheus(vals)


def make_handler(state: ServerState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        def _json(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                if state.error:  # incl. heartbeat latch (on_failure sets it)
                    self._json(503, {"status": "error",
                                     "detail": state.error})
                else:
                    body = {"status": "ok"}
                    if state.heartbeat is not None:
                        body["heartbeats"] = state.heartbeat.beats
                    self._json(200, body)
            elif self.path == "/metrics":
                body = state.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/generate":
                self._json(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                if "tokens" in body:
                    tokens = [int(t) for t in body["tokens"]]
                else:
                    tokens = state.tok.encode(str(body.get("prompt", "")))
                vocab = state.sched.engine.cfg.vocab_size
                if any(t >= vocab or t < 0 for t in tokens):
                    raise ValueError("token id out of range")
                if not tokens:
                    raise ValueError("empty prompt")
                max_seq = state.sched.engine.cache.max_seq
                max_tokens = int(body.get("max_tokens", 64))
                if max_tokens < 1:
                    raise ValueError("max_tokens must be >= 1")
                if len(tokens) + max_tokens > max_seq:
                    raise ValueError(
                        f"prompt+max_tokens exceeds max_seq {max_seq}")
                temperature = float(body.get("temperature", 0.0))
                stop = int(body.get("stop_token",
                                    -1 if state.tok.eos_id is None
                                    else state.tok.eos_id))
            except (ValueError, TypeError, KeyError) as e:
                self._json(400, {"error": str(e)})
                return
            if state.error:
                self._json(503, {"error": "server wedged: " + state.error})
                return
            t0 = time.monotonic()

            try:
                req, q = state.submit(tokens, max_tokens, temperature, stop)
            except ValueError as e:  # can never fit the page pool
                self._json(400, {"error": str(e)})
                return
            except RuntimeError as e:  # wedged while we were admitting
                self._json(503, {"error": str(e)})
                return
            if req is None:
                self._json(429, {"error": "queue full"})
                return

            if body.get("stream"):
                self._stream(req, q, t0)
            else:
                toks = []
                while True:
                    try:
                        tok = q.get(timeout=0.5)
                    except queue.Empty:
                        if req.done or state.error:
                            break  # wedged/hung: answer with partials
                        if not self._client_alive():
                            if state.lock.acquire(timeout=2.0):
                                try:
                                    state.sched.cancel(req)
                                finally:
                                    state.lock.release()
                            return
                        continue
                    if tok is None:
                        break
                    toks.append(tok)
                if req.state == "cancelled" or (state.error
                                                and not req.done):
                    self._json(503, {"error": "generation aborted: "
                                     + (state.error or "cancelled"),
                                     "partial_tokens": toks})
                    return
                self._json(200, {
                    "tokens": toks,
                    "text": state.tok.decode(toks),
                    "ttft_s": req.ttft,
                    "total_s": time.monotonic() - t0,
                })

        def _client_alive(self) -> bool:
            """Peek the socket: a closed peer reads as EOF (b'')."""
            import socket
            try:
                data = self.connection.recv(1, socket.MSG_PEEK
                                            | socket.MSG_DONTWAIT)
                return data != b""
            except (BlockingIOError, InterruptedError):
                return True          # no data pending = still connected
            except OSError:
                return False

        def _stream(self, req, q, t0) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(data: bytes) -> None:
                self.wfile.write(f"{len(data):X}\r\n".encode() + data
                                 + b"\r\n")

            try:
                while True:
                    try:
                        # bounded wait: a hung device must not pin this
                        # handler thread forever — bail once the request
                        # is drained OR the server wedged (a truly hung
                        # tick never delivers the sentinel)
                        tok = q.get(timeout=0.5)
                    except queue.Empty:
                        if req.done or state.error:
                            break
                        continue
                    if tok is None:
                        break
                    piece = state.tok.decode([tok])
                    msg = json.dumps({"token": tok, "text": piece})
                    chunk(f"data: {msg}\n\n".encode())
                if req.state == "cancelled" or (state.error
                                                and not req.done):
                    err = json.dumps({"error": "generation aborted: "
                                      + (state.error or "cancelled")})
                    chunk(f"data: {err}\n\n".encode())
                else:
                    chunk(b"data: [DONE]\n\n")
                chunk(b"")  # terminating chunk
            except (BrokenPipeError, ConnectionResetError):
                # client went away: stop generating for a dead socket.
                # Best-effort cancel: a hung tick may hold the lock
                # forever — leaking the request is better than pinning
                # this handler thread on acquire.
                if state.lock.acquire(timeout=2.0):
                    try:
                        state.sched.cancel(req)
                    finally:
                        state.lock.release()

    return Handler


def serve_forever(scheduler, tokenizer, host: str = "0.0.0.0",
                  port: int = 8000, max_queue: int = 256,
                  ready_event: Optional[threading.Event] = None,
                  heartbeat=None):
    """Blocking serve loop. `ready_event` is set once listening (tests).

    `heartbeat`: a HeartbeatMonitor to use (callers may tune interval /
    misses / probe); defaults to the LOCAL device probe. Deliberately so
    even multi-host: an idle-timer collective probe would be issued in
    unsynchronized order across hosts and desync the SPMD program
    stream — on a pod each host watchdogs its own chip, and a dead PEER
    surfaces as the next real tick stalling on its collective, which
    the staleness latch catches.
    """
    from butterfly_tpu.obs.health import HeartbeatMonitor
    if heartbeat is None:
        heartbeat = HeartbeatMonitor()
    state = ServerState(scheduler, tokenizer, max_queue,
                        heartbeat=heartbeat)
    state.thread.start()
    httpd = ThreadingHTTPServer((host, port), make_handler(state))
    state.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        state.stop.set()
        if state.heartbeat is not None:
            state.heartbeat.stop()
        httpd.server_close()
    return 0


def run_server(args) -> int:
    """`butterfly serve` entrypoint (serve/cli.py)."""
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler
    from butterfly_tpu.serve.cli import build_mesh, load_params, resolve_model
    from butterfly_tpu.utils.tokenizer import load_tokenizer

    model = resolve_model(args)
    tok = load_tokenizer(args.tokenizer or args.ckpt)
    mesh = build_mesh(args)
    params = load_params(model, args)
    rt = RuntimeConfig(max_batch_size=args.max_batch,
                       max_seq_len=args.max_seq, page_size=args.page_size,
                       top_k=args.top_k, top_p=args.top_p,
                       max_queue=args.max_queue)
    engine = ServingEngine(model, params, rt, mesh=mesh)
    sched = Scheduler(engine)
    # Warm the serving programs (fresh-chunk prefill, warm-chunk
    # continuation, batched decode) before listening: the first user
    # doesn't pay 20-40s of XLA compile, and the heartbeat watchdog
    # never mistakes the startup compile for a dead device.
    print("[butterfly] warming serving programs...", flush=True)
    warm_len = min(2 * rt.prefill_chunk, rt.max_seq_len - 4)
    warms = [sched.submit([1] * max(1, warm_len), max_new_tokens=2),
             sched.submit([1], max_new_tokens=2)]  # smallest bucket too
    sched.run_until_done()
    assert all(w.done for w in warms)
    mesh_desc = "" if mesh is None else \
        " mesh=" + "x".join(f"{k}{v}" for k, v in mesh.shape.items() if v > 1)
    print(f"[butterfly] serving {args.model} on {args.host}:{args.port} "
          f"(slots={rt.max_batch_size}, pages={engine.cache.num_pages - 1}"
          f"x{rt.page_size}tok{mesh_desc})", flush=True)
    return serve_forever(sched, tok, args.host, args.port,
                         max_queue=rt.max_queue)
