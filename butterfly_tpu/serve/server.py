"""HTTP serving: the reference's planned client-facing API layer
(/root/reference/CLAUDE.md:23) over the continuous-batching scheduler.

stdlib-only (ThreadingHTTPServer — no web framework dependencies, per the
zero-egress environment):

* POST /generate  {"prompt": str | "tokens": [int], "max_tokens"
                   (alias "max_new_tokens"), "temperature", "stop_token",
                   "stream": bool, "speculative": bool (default true —
                   set false to opt one request out of draft acceptance
                   on a --speculate server; composes with temperature)}
  -> {"text", "tokens", "ttft_s", "total_s"}; with "stream": true the
  response is SSE (`data: {"token": id, "text": piece}` per token,
  terminated by `data: [DONE]`).
* POST /v1/completions  OpenAI-completions-compatible (single choice):
  {"prompt": str | [int], "max_tokens", "temperature", "stop" (string or
  up to 4 strings, matched on decoded text with streaming holdback),
  "stream"} -> {"id", "object": "text_completion", "choices": [{"text",
  "finish_reason"}], "usage"}; streaming sends OpenAI-style SSE chunks.
* GET /metrics    Prometheus text (obs/metrics.py + the typed registry's
  histogram series — obs/registry.py)
* GET /health     {"status": "ok", "role", "queue_depth", "active",
  "free_pages", "inflight_depth"} — one cheap JSON probe carrying every
  load/placement signal the router AND the fleet control plane read
  (queue depth + page headroom + pipeline depth + replica role; no
  Prometheus text scrape, no second poll path); 503 with a detail
  string when wedged.
* GET /kv/pages?hashes=h1,h2,...   export registered prefix-cache KV
  pages by chain hash (fleet/kvtransfer.py payload: base64 page bytes +
  geometry; the leading registered run ships, the rest come back
  "missing"). Requires --prefix-caching (501 otherwise).
* POST /kv/import   land an exported payload into the local pool +
  prefix registry as warm pages (the decode half of the disaggregated
  prefill/decode handoff); 409 on KV geometry mismatch.
* GET /debug/requests[?n=K]   recent per-request trace timelines as JSON
  (obs/trace.py; requires the scheduler to be built with a Tracer —
  returns {"enabled": false} otherwise). Clients may tag requests with
  an `X-Request-Id` header or a `request_id` body field; the id rides
  the trace verbatim so client logs join server timelines, and is
  echoed back as an `X-Request-Id` response header on every response
  (JSON and SSE) so clients/routers correlate without parsing bodies.

One scheduler thread owns all device work (ticks); HTTP handler threads
only enqueue requests and wait on per-request queues — JAX never runs on
more than one host thread.
"""
from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

from butterfly_tpu.obs.metrics import ThroughputWindow, render_prometheus


class LockTimeout(RuntimeError):
    """A handler-thread path timed out acquiring the serving lock (a
    slow or hung tick holds it). Every HTTP path that can raise this
    answers 503 + Retry-After instead of pinning the handler thread —
    and the timeout is counted (server_lock_timeouts_total)."""


class ProfilerUnavailable(RuntimeError):
    """The jax.profiler capture could not start (no profiler plugin in
    this build, a concurrent trace already running, an unwritable
    logdir). POST /debug/profile answers 501 with the reason — the
    graceful no-xprof fallback, never a crash."""


class ProfilerBusy(RuntimeError):
    """A capture is already in flight: one at a time (jax.profiler is
    process-global). POST /debug/profile answers 409."""


class StopSequenceMatcher:
    """Incremental stop-sequence detection over streamed text.

    OpenAI's `stop` parameter is a string (or up to 4 strings) that ends
    generation, with the matched text EXCLUDED from the output. Matching
    is on decoded text, not token ids, so a stop sequence split across
    token boundaries still hits. `feed` returns the text that is safe to
    release now: everything except the longest trailing run that could
    still grow into a stop sequence (the holdback keeps streaming from
    ever emitting a byte of the stop text).
    """

    def __init__(self, stops):
        self.stops = [s for s in stops if s]
        self._maxlen = max((len(s) for s in self.stops), default=0)
        self.text = ""       # everything fed so far
        self.released = 0    # chars already returned to the caller
        self.hit = False

    def feed(self, piece: str) -> str:
        if self.hit:
            return ""
        prev_len = len(self.text)
        self.text += piece
        # A match cannot start in already-released text (it would have
        # hit or been held back when that text arrived), so only scan
        # from maxlen-1 chars before the new piece — O(piece), not
        # O(total generation), per token.
        scan_from = max(self.released, prev_len - self._maxlen + 1, 0)
        cut = min((i for i in (self.text.find(s, scan_from)
                               for s in self.stops) if i >= 0), default=-1)
        if cut >= 0:
            self.hit = True
            out = self.text[self.released:cut]
            self.released = cut
            return out
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self.text)), 0, -1):
                if self.text.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        safe_to = len(self.text) - hold
        out = self.text[self.released:safe_to] \
            if safe_to > self.released else ""
        self.released = max(self.released, safe_to)
        return out

    def flush(self) -> str:
        """Release the holdback (generation ended without a hit)."""
        if self.hit:
            return ""
        out = self.text[self.released:]
        self.released = len(self.text)
        return out


class ServerState:
    def __init__(self, scheduler, tokenizer, max_queue: int = 256,
                 heartbeat=None, model_name: str = "butterfly",
                 role: str = "both"):
        self.sched = scheduler
        self.tok = tokenizer
        self.model_name = model_name  # echoed by /v1/completions
        # fleet placement advertisement (prefill | decode | both):
        # carried on /health so the control plane learns the tier from
        # the same probe the router pool already runs. Advisory only —
        # a prefill replica still decodes if asked (the control plane
        # just stops sending decodes there).
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown replica role {role!r}")
        self.role = role
        self.lock = threading.Lock()       # guards scheduler state
        self.wake = threading.Event()      # new work signal
        self.stop = threading.Event()
        self.max_queue = max_queue
        self.throughput = ThroughputWindow()
        self.t_start = time.monotonic()
        self.error: str = ""               # set => serving is wedged: 503s
        # lock-acquire timeouts are multi-writer (any handler thread),
        # unlike the scheduler registry's single-writer instruments —
        # guard the counter with its own tiny lock
        self._c_lock_timeout = scheduler.registry.counter(
            "server_lock_timeouts_total",
            "HTTP paths that timed out acquiring the serving lock (a "
            "slow or hung tick held it) and answered 503 + Retry-After "
            "instead of pinning a handler thread")
        self._mlock = threading.Lock()
        # Admission tolerates a much longer lock wait than the
        # read-only surfaces: the scheduler thread legitimately holds
        # the lock for SECONDS when a tick compiles a fresh XLA shape
        # (20-40s cold on TPU), and 503ing arrivals through a compile
        # would turn every unwarmed bucket's first burst into spurious
        # errors. A truly HUNG tick is caught by the heartbeat latch
        # (which wedges the server and fails submit fast), so this
        # bound is a backstop, not the primary hang defense.
        self.submit_lock_timeout = 30.0
        # -- live on-demand profiling (ISSUE 15) -----------------------------
        # POST /debug/profile hands the LOOP THREAD a (duration, logdir)
        # request; the loop starts/stops the jax.profiler trace BETWEEN
        # its lock-holding tick sections, so the capture brackets live
        # ticks without the handler (or the capture) ever holding the
        # serving lock — admission proceeds normally for the whole
        # capture window. _profile_guard (its own tiny mutex, never
        # self.lock) only serializes concurrent capture requests.
        self._profile_guard = threading.Lock()
        self._profile_pending: Optional[tuple] = None
        self._profile_active: Optional[tuple] = None
        self._profile_result: Optional[dict] = None
        self._profile_done = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True)
        # Optional HeartbeatMonitor (obs/health.py): the scheduler
        # thread beats after every tick and runs the probe in-thread
        # when idle (JAX stays on ONE host thread); the monitor's
        # watchdog thread only watches wall-clock staleness, so a HUNG
        # tick latches too. On latch: wedge serving (503s) and drain
        # host-side only (abort_all never touches the dead device).
        self.heartbeat = heartbeat
        if heartbeat is not None:
            prev = heartbeat.on_failure
            if prev is None:
                heartbeat.on_failure = self._on_heartbeat_failure
            else:  # chain a caller-provided hook, don't discard it
                def chained(exc, _prev=prev):
                    self._on_heartbeat_failure(exc)
                    _prev(exc)
                heartbeat.on_failure = chained
            if not heartbeat._thread.is_alive():
                heartbeat.start()
            if not heartbeat.healthy:  # latched before we were handed it
                self._on_heartbeat_failure(None)

    def _on_heartbeat_failure(self, exc) -> None:
        # Runs on the watchdog thread: host-only bookkeeping, no JAX.
        # In the hung-tick scenario the scheduler thread HOLDS self.lock
        # (stuck inside a device call) — waiting would deadlock the
        # recovery. Try briefly; on timeout set the error ONLY: the
        # watchdog cannot distinguish hung from slow, and draining
        # concurrently with a slow-but-alive tick would corrupt
        # scheduler state. The scheduler loop drains itself at its next
        # iteration (error check in _loop); a truly hung tick never
        # reaches it, but then its host state is frozen and 503s flow.
        self.error = f"heartbeat failed: {self.heartbeat.last_error}"
        # wedge latch -> flight-recorder post-mortem: freeze the event
        # ring NOW (the tick loop may be the thing that died, so the
        # per-tick trigger poll can't be relied on to fire)
        fr = getattr(self.sched, "flightrec", None)
        if fr is not None:
            fr.note("wedge", error=self.error)
            fr.trigger("wedge", {"error": self.error})
        if self.acquire_lock():
            try:
                self.sched.abort_all()
            finally:
                self.lock.release()

    def acquire_lock(self, timeout: float = 2.0) -> bool:
        """Bounded serving-lock acquire for handler/watchdog threads:
        a hung tick may hold the lock forever, and no HTTP path may pin
        its thread on it. False = timed out (counted); the HTTP paths
        then answer 503 + Retry-After via LockTimeout."""
        if self.lock.acquire(timeout=timeout):
            return True
        with self._mlock:
            self._c_lock_timeout.inc()
        return False

    def _locked(self, timeout: float = 2.0):
        """Context manager: bounded acquire or LockTimeout."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            if not self.acquire_lock(timeout=timeout):
                raise LockTimeout(
                    "serving lock busy (slow or hung tick); retry")
            try:
                yield
            finally:
                self.lock.release()
        return cm()

    # -- scheduler thread ----------------------------------------------------

    def _loop(self) -> None:
        while not self.stop.is_set():
            self._maybe_profile()
            if self.error:
                # wedged (in-tick exception, or the watchdog latched
                # while we were mid-tick): drain remaining work under
                # the lock — the single host-only drain path — and
                # idle. Beat the heartbeat: this loop is alive and
                # wedged-by-design; re-latching on staleness would
                # clobber the real root cause in self.error.
                with self.lock:
                    if self.sched.has_work:
                        self.sched.abort_all()
                if self.heartbeat is not None:
                    self.heartbeat.beat()
                self.wake.wait(timeout=0.2)
                self.wake.clear()
                continue
            try:
                with self.lock:
                    has_work = self.sched.has_work
                    made = self.sched.tick() if has_work else 0
            except Exception as e:  # device/OOM errors must not wedge:
                # set the error; the wedged branch above drains on the
                # next iteration (one drain path, not two)
                self.error = f"{type(e).__name__}: {e}"
                continue
            if has_work:
                if made:
                    self.throughput.record(made)
                if self.heartbeat is not None:
                    self.heartbeat.beat()  # a completed tick IS liveness
            else:
                if self.heartbeat is not None:
                    self.heartbeat.maybe_probe()  # idle: probe in-thread
                self.wake.wait(timeout=0.05)
                self.wake.clear()

    # -- live on-demand profiling (loop thread + handler threads) -------------

    @staticmethod
    def _profiler_start(logdir: str) -> None:
        """Start the process-global jax.profiler trace (split out so
        tests can force the no-xprof 501 path by monkeypatching)."""
        import jax
        jax.profiler.start_trace(logdir)

    @staticmethod
    def _profiler_stop() -> None:
        import jax
        jax.profiler.stop_trace()

    def _maybe_profile(self) -> None:
        """Runs on the scheduler loop thread, OUTSIDE the serving lock:
        start a pending capture, stop an expired one. The capture
        therefore brackets whole ticks of the live loop and never
        blocks admission — the serving lock is untouched on this path
        (the BTF004 contract; pinned by test)."""
        req = self._profile_pending
        if req is not None and self._profile_active is None:
            self._profile_pending = None
            dur_s, logdir = req
            t0 = time.monotonic()
            try:
                self._profiler_start(logdir)
            except Exception as e:  # no profiler plugin / busy / bad dir
                self._profile_result = {
                    "error": f"{type(e).__name__}: {e}"}
                self._profile_done.set()
                return
            self._profile_active = (t0 + dur_s, logdir, t0)
        act = self._profile_active
        if act is not None and time.monotonic() >= act[0]:
            self._profile_active = None
            deadline, logdir, t0 = act
            result = {"logdir": logdir,
                      "duration_s": time.monotonic() - t0}
            try:
                self._profiler_stop()
            except Exception as e:
                result["error"] = f"{type(e).__name__}: {e}"
            self._profile_result = result
            self._profile_done.set()

    def request_profile(self, duration_ms: float,
                        logdir: Optional[str] = None) -> dict:
        """POST /debug/profile body -> result. Blocks the HANDLER
        thread (bounded: duration + slack) while the loop thread
        captures; never touches the serving lock, so admission and
        every other endpoint proceed normally through the capture."""
        import glob
        import tempfile
        duration_ms = min(max(float(duration_ms), 10.0), 60000.0)
        if not self._profile_guard.acquire(blocking=False):
            raise ProfilerBusy("a profile capture is already running")
        try:
            if logdir is None:
                logdir = tempfile.mkdtemp(prefix="butterfly_profile_")
            self._profile_result = None
            self._profile_done.clear()
            self._profile_pending = (duration_ms / 1e3, str(logdir))
            self.wake.set()  # an idle loop wakes to start the capture
            if not self._profile_done.wait(timeout=duration_ms / 1e3 + 30.0):
                # a truly hung tick never reaches _maybe_profile: drop
                # the request so a later loop iteration doesn't start a
                # stale capture, and tell the client
                self._profile_pending = None
                raise ProfilerUnavailable(
                    "capture did not complete (tick loop stalled?)")
            res = dict(self._profile_result or {})
        finally:
            self._profile_guard.release()
        if "error" in res:
            raise ProfilerUnavailable(res["error"])
        res["duration_ms"] = duration_ms
        res["files"] = sorted(
            str(Path(p).relative_to(res["logdir"])) for p in glob.glob(
                res["logdir"] + "/**/*", recursive=True)
            if Path(p).is_file())
        return res

    def debug_ticks(self, n: Optional[int] = None,
                    since: Optional[int] = None) -> dict:
        """GET /debug/ticks body: the bounded per-tick timeline ring
        (obs/ticklog.py). Reads only the ring's own lock — a wedged
        scheduler can still be inspected. `since` pages by tick seq
        (tick_report --follow's incremental poll)."""
        log = getattr(self.sched, "ticklog", None)
        if log is None:
            return {"enabled": False, "ticks": []}
        return {"enabled": True, **log.dump(n, since=since)}

    def debug_flightrecorder(self, n: Optional[int] = None) -> dict:
        """GET /debug/flightrecorder body: the anomaly event ring +
        retained trigger artifacts ({"enabled": false} when the
        scheduler was built without a recorder)."""
        fr = getattr(self.sched, "flightrec", None)
        if fr is None:
            return {"enabled": False, "events": [], "dumps": []}
        return fr.dump(n)

    def debug_timeseries(self, since: Optional[int] = None,
                         signals=None) -> dict:
        """GET /debug/timeseries body: the periodic signal-history ring
        (obs/timeseries.py SignalRecorder). Reads only the ring's own
        lock — the /debug/ticks wedge-readability contract.
        ({"enabled": false} when serving with --timeseries-interval 0.)
        """
        rec = getattr(self.sched, "timeseries", None)
        if rec is None:
            return {"enabled": False, "samples": [], "alerts": []}
        return rec.dump(since=since, signals=signals)

    # -- handler-thread API ---------------------------------------------------

    def submit(self, tokens, max_tokens, temperature, stop_token,
               request_id=None, priority="interactive", deadline_s=None,
               speculative=True):
        """Admit one request. Returns (req, queue); (None, retry_after
        float) when SLO-aware admission SHED it (predicted TTFT busts
        the declared objective — the handler answers 429 with the
        computed Retry-After); (None, None) when the waiting queue is
        full. Raises LockTimeout when the serving lock is held by a
        slow/hung tick."""
        q: queue.Queue = queue.Queue()

        def on_token(req, token):
            q.put(token)

        def on_finish(req):
            q.put(None)  # completion sentinel (after the last on_token)

        with self._locked(timeout=self.submit_lock_timeout):
            # re-check under the lock: the heartbeat may have wedged the
            # server between the handler's check and this admission
            if self.error:
                raise RuntimeError("server wedged: " + self.error)
            retry_after = self.sched.shed_decision(len(tokens), priority)
            if retry_after is not None:
                return None, retry_after
            if len(self.sched.waiting) >= self.max_queue:
                return None, None
            req = self.sched.submit(tokens, max_new_tokens=max_tokens,
                                    temperature=temperature,
                                    stop_token=stop_token,
                                    on_token=on_token, on_finish=on_finish,
                                    request_id=request_id,
                                    priority=priority,
                                    deadline_s=deadline_s,
                                    speculative=speculative)
        self.wake.set()
        return req, q

    def metrics_text(self) -> str:
        with self._locked():
            vals = self.sched.metrics()
        vals["tokens_per_sec"] = self.throughput.rate()
        vals["uptime_seconds"] = time.monotonic() - self.t_start
        return render_prometheus(vals,
                                 registry=getattr(self.sched, "registry",
                                                  None))

    def export_kv(self, hex_hashes) -> dict:
        """GET /kv/pages body: export registered pages by chain hash.
        Under the serving lock — the scheduler thread must not donate
        the pools (every decode/prefill dispatch donates them) while
        the export gather reads page bytes out."""
        from butterfly_tpu.fleet.kvtransfer import export_payload
        with self._locked():
            if self.error:
                raise RuntimeError("server wedged: " + self.error)
            # full reconcile (cause="flush") before page bytes leave
            # the process: drains every in-flight block and flushes the
            # write-combined KV window, so the exported pool bytes are
            # never missing staged-but-unflushed K/V
            self.sched._drain_inflight("flush")
            return export_payload(self.sched, hex_hashes)

    def import_kv(self, payload: dict) -> dict:
        """POST /kv/import body -> result. Under the serving lock: the
        import claims pages from the same free/evictable lists
        admissions allocate from."""
        from butterfly_tpu.fleet.kvtransfer import import_payload
        with self._locked():
            if self.error:
                raise RuntimeError("server wedged: " + self.error)
            return import_payload(self.sched, payload)

    def count_deadline(self, where: str) -> None:
        """Handler-thread deadline accounting (requests 504ed before
        they ever reached the scheduler): the scheduler's counter
        family is single-writer, so go through the metrics lock."""
        with self._mlock:
            self.sched._c_deadline.labels(where).inc()

    def debug_requests(self, n: Optional[int] = None,
                       request_id: Optional[str] = None) -> dict:
        """Recent per-request trace timelines (the /debug/requests
        body). Reads only the tracer's own lock — a wedged scheduler
        (hung tick holding self.lock) can still be inspected.
        `request_id` filters to one client id's timelines and drops the
        global ring (the fleet trace merge wants exactly one request's
        events, not every tick in the window)."""
        tracer = getattr(self.sched, "trace", None)
        if tracer is None:
            return {"enabled": False, "requests": []}
        dump = tracer.dump(n_requests=n, request_id=request_id,
                           n_global=0 if request_id is not None else None)
        dump["enabled"] = True
        return dump


def make_handler(state: ServerState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        # client correlation id for the in-flight request: set from the
        # X-Request-Id header at dispatch, refined by _parse_request when
        # the id arrives as a body field instead. Echoed back as a
        # response header on every response (JSON and SSE) so clients —
        # and the multi-replica router — can correlate without parsing
        # bodies.
        _rid: Optional[str] = None

        def _json(self, code: int, obj, headers=None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if self._rid:
                self.send_header("X-Request-Id", self._rid)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._rid = self._header_rid()
            if self.path == "/health":
                if state.error:  # incl. heartbeat latch (on_failure sets it)
                    self._json(503, {"status": "error",
                                     "detail": state.error})
                else:
                    # every field is deliberately read WITHOUT
                    # state.lock: len() on the scheduler's deque/list
                    # and the allocator's free-list length are atomic
                    # enough for a load probe (one update stale at
                    # worst), and /health must stay responsive even when
                    # a slow tick holds the lock — the router's prober
                    # times out a hanging probe into "degraded". One
                    # probe carries the full control-plane signal set
                    # (role, page headroom, pipeline depth): the fleet
                    # tier needs no second poll path.
                    body = {"status": "ok",
                            "role": state.role,
                            "queue_depth": len(state.sched.waiting),
                            "active": len(state.sched._all_live),
                            "free_pages": state.sched.alloc.free_pages,
                            "inflight_depth":
                                len(state.sched._inflight),
                            # wall-clock stamp for the prober's clock-
                            # offset estimate (router/pool.py): the
                            # fleet trace merge places this replica's
                            # monotonic events on the control plane's
                            # clock via offset = now_wall - probe RTT
                            # midpoint
                            "now_wall": time.time()}
                    if state.heartbeat is not None:
                        body["heartbeats"] = state.heartbeat.beats
                    self._json(200, body)
            elif self.path.split("?")[0] == "/kv/pages":
                self._handle_kv_export()
            elif self.path == "/metrics":
                try:
                    body = state.metrics_text().encode()
                except LockTimeout as e:
                    self._json(503, {"error": str(e)},
                               headers={"Retry-After": "1"})
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.split("?")[0] == "/debug/requests":
                q = self._query_debug()
                self._json(200, state.debug_requests(
                    q["n"], q["request_id"]))
            elif self.path.split("?")[0] == "/debug/ticks":
                q = self._query_debug()
                self._json(200, state.debug_ticks(q["n"], q["since"]))
            elif self.path.split("?")[0] == "/debug/flightrecorder":
                q = self._query_debug()
                self._json(200, state.debug_flightrecorder(q["n"]))
            elif self.path.split("?")[0] == "/debug/timeseries":
                q = self._query_debug()
                self._json(200, state.debug_timeseries(
                    q["since"], q["signals"]))
            else:
                self._json(404, {"error": "not found"})

        def _header_rid(self) -> Optional[str]:
            rid = self.headers.get("X-Request-Id")
            return str(rid)[:128] if rid is not None else None

        def _query_debug(self):
            """Shared /debug/* query parsing: ?n=K limit, ?request_id=
            client-id filter, ?since=SEQ incremental pagination
            (ticks/timeseries), ?signals=a,b signal-name filter
            (timeseries). Absent/bad fields parse as None — a bad query
            degrades to the full dump, never a 500."""
            from urllib.parse import parse_qs, urlparse
            out = {"n": None, "request_id": None, "since": None,
                   "signals": None}
            try:
                qs = parse_qs(urlparse(self.path).query)
                if "n" in qs:
                    out["n"] = int(qs["n"][0])
                if "request_id" in qs:
                    out["request_id"] = str(qs["request_id"][0])[:128]
                if "since" in qs:
                    out["since"] = int(qs["since"][0])
                if "signals" in qs:
                    out["signals"] = [s for s in
                                      ",".join(qs["signals"]).split(",")
                                      if s]
            except (ValueError, TypeError, IndexError):
                pass
            return out

        def do_POST(self):
            self._rid = self._header_rid()
            if self.path == "/generate":
                self._handle_generate()
            elif self.path == "/v1/completions":
                self._handle_completions()
            elif self.path == "/kv/import":
                self._handle_kv_import()
            elif self.path == "/debug/profile":
                self._handle_profile()
            else:
                self._json(404, {"error": "not found"})

        def _handle_kv_export(self):
            from urllib.parse import parse_qs, urlparse
            try:
                qs = parse_qs(urlparse(self.path).query)
                hashes = [h for h in
                          ",".join(qs.get("hashes", [])).split(",") if h]
                for h in hashes:  # validate before touching the lock
                    bytes.fromhex(h)
            except (ValueError, TypeError):
                self._json(400, {"error": "hashes must be comma-separated "
                                          "hex chain digests"})
                return
            if not hashes:
                self._json(400, self._kv_err("missing ?hashes= query"))
                return
            try:
                self._json(200, state.export_kv(hashes))
            except LookupError as e:  # no prefix registry on this replica
                self._json(501, self._kv_err(str(e)))
            except LockTimeout as e:  # tick holds the lock: back off
                self._json(503, self._kv_err(str(e)),
                           headers={"Retry-After": "1"})
            except RuntimeError as e:  # wedged
                self._json(503, self._kv_err(str(e)))

        def _kv_err(self, msg: str) -> dict:
            """KV-transfer error body: carries the request id (when the
            control plane forwarded one) so a failed handoff leg is
            attributable to its distributed request from logs alone —
            the header echo alone doesn't survive into log lines."""
            body = {"error": msg}
            if self._rid:
                body["request_id"] = self._rid
            return body

        def _handle_profile(self):
            """POST /debug/profile {duration_ms, logdir}: a
            duration-bounded jax.profiler capture of the LIVE tick
            loop. The capture runs on the scheduler loop thread and
            never holds the serving lock — only this handler thread
            blocks (bounded) waiting for the artifact. 501 = no xprof
            in this build (graceful fallback, with reason); 409 = a
            capture is already in flight."""
            try:
                body = self._read_body()
                duration_ms = float(body.get("duration_ms", 1000.0))
                logdir = body.get("logdir")
                if logdir is not None:
                    logdir = str(logdir)
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            try:
                self._json(200, state.request_profile(duration_ms, logdir))
            except ProfilerBusy as e:
                self._json(409, {"error": str(e)})
            except ProfilerUnavailable as e:
                self._json(501, {"error": str(e),
                                 "reason": "no-xprof or capture failed"})

        def _handle_kv_import(self):
            try:
                payload = self._read_body()
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            try:
                self._json(200, state.import_kv(payload))
            except LookupError as e:
                self._json(501, self._kv_err(str(e)))
            except (ValueError, KeyError, TypeError) as e:
                # geometry mismatch / malformed page entries: refusing
                # is the safety property — a mismatched import would
                # alias garbage K/V under a valid-looking chain hash
                self._json(409, self._kv_err(f"{e}"))
            except LockTimeout as e:  # tick holds the lock: back off
                self._json(503, self._kv_err(str(e)),
                           headers={"Retry-After": "1"})
            except RuntimeError as e:  # wedged
                self._json(503, self._kv_err(str(e)))

        def _read_body(self) -> dict:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body

        def _parse_request(self, body: dict):
            """Shared validation -> (tokens, max_tokens, temperature,
            stop, rid, priority, deadline_ms, speculative).

            Accepts our native schema and the OpenAI-completions field
            names (`prompt` may be a string OR a token-id list there;
            `max_new_tokens` is accepted as a `max_tokens` alias).
            `deadline_ms` (body) / `X-Deadline-Ms` (header, wins) is
            the REMAINING latency budget at arrival — routers and the
            fleet control plane decrement it per hop; `priority` /
            `X-Priority` selects the admission class. `speculative`
            (default true) composes with the sampling params: false
            opts this request's slot out of draft acceptance on a
            --speculate server (it still rides the batched verify,
            emitting one exact plain-decode sample per round); ignored
            when the server runs without --speculate."""
            if "tokens" in body:
                tokens = [int(t) for t in body["tokens"]]
            else:
                prompt = body.get("prompt", "")
                if isinstance(prompt, list):  # OpenAI token-id form
                    tokens = [int(t) for t in prompt]
                else:
                    tokens = state.tok.encode(str(prompt))
            vocab = state.sched.engine.cfg.vocab_size
            if any(t >= vocab or t < 0 for t in tokens):
                raise ValueError("token id out of range")
            if not tokens:
                raise ValueError("empty prompt")
            max_seq = state.sched.engine.cache.max_seq
            max_tokens = int(body.get("max_tokens",
                                      body.get("max_new_tokens", 64)))
            if max_tokens < 1:
                raise ValueError("max_tokens must be >= 1")
            if len(tokens) + max_tokens > max_seq:
                raise ValueError(
                    f"prompt+max_tokens exceeds max_seq {max_seq}")
            temperature = float(body.get("temperature", 0.0))
            stop = int(body.get("stop_token",
                                -1 if state.tok.eos_id is None
                                else state.tok.eos_id))
            # client trace-correlation id: header wins over body field
            rid = self.headers.get("X-Request-Id") \
                or body.get("request_id")
            rid = str(rid)[:128] if rid is not None else None
            self._rid = rid  # echoed on the response (incl. SSE headers)
            priority = str(self.headers.get("X-Priority")
                           or body.get("priority") or "interactive")
            if priority not in ("interactive", "batch"):
                raise ValueError(f"unknown priority {priority!r}: "
                                 "expected 'interactive' or 'batch'")
            dl = self.headers.get("X-Deadline-Ms")
            if dl is None:
                dl = body.get("deadline_ms")
            deadline_ms = float(dl) if dl is not None else None
            if deadline_ms is not None and not deadline_ms == deadline_ms:
                raise ValueError("deadline_ms must be a number")  # NaN
            speculative = body.get("speculative", True)
            if not isinstance(speculative, bool):
                raise ValueError("speculative must be a boolean")
            return (tokens, max_tokens, temperature, stop, rid,
                    priority, deadline_ms, speculative)

        def _deadline_504(self, where: str, deadline_ms,
                          elapsed_s: float, openai: bool,
                          partial=None) -> None:
            """The deadline-exceeded terminal response: 504 with enough
            detail (where it died, elapsed vs budget) that a client or
            the fleet trace can attribute the miss without guessing."""
            detail = {"where": where,
                      "deadline_ms": deadline_ms,
                      "elapsed_ms": elapsed_s * 1e3}
            if openai:
                body = {"error": {"message": "deadline exceeded "
                                             f"({where})",
                                  "type": "timeout_error", **detail}}
            else:
                body = {"error": "deadline exceeded", **detail}
                if partial is not None:
                    body["partial_tokens"] = partial
            self._json(504, body)

        def _admit(self, body: dict, openai: bool = False):
            """Parse + submit; handles every error response (in the
            OpenAI error-envelope shape when `openai`). Returns
            (req, queue, deadline_ms) or None if a response was already
            sent."""
            def err(code: int, msg: str, etype: str,
                    headers=None) -> None:
                if openai:
                    self._json(code, {"error": {"message": msg,
                                                "type": etype}},
                               headers=headers)
                else:
                    self._json(code, {"error": msg}, headers=headers)

            try:
                (tokens, max_tokens, temperature, stop, rid, priority,
                 deadline_ms, speculative) = self._parse_request(body)
            except (ValueError, TypeError, KeyError) as e:
                err(400, str(e), "invalid_request_error")
                return None
            if state.error:
                err(503, "server wedged: " + state.error, "server_error")
                return None
            now = time.monotonic()
            deadline_s = None
            if deadline_ms is not None:
                if deadline_ms <= 0:
                    # arrived already expired: terminal 504, never a
                    # queue slot (the scheduler would only scrub it)
                    state.count_deadline("admission")
                    self._deadline_504("admission", deadline_ms, 0.0,
                                       openai)
                    return None
                deadline_s = now + deadline_ms / 1e3
            try:
                req, q = state.submit(tokens, max_tokens, temperature, stop,
                                      request_id=rid, priority=priority,
                                      deadline_s=deadline_s,
                                      speculative=speculative)
            except ValueError as e:  # can never fit the page pool
                err(400, str(e), "invalid_request_error")
                return None
            except LockTimeout as e:  # slow/hung tick holds the lock
                err(503, str(e), "server_error",
                    headers={"Retry-After": "1"})
                return None
            except RuntimeError as e:  # wedged while we were admitting
                err(503, str(e), "server_error")
                return None
            if req is None:
                # explicit backoff signal: the router (and well-behaved
                # clients) should stop hammering a saturated replica
                # instead of retry-spinning on 429s. q carries the
                # computed Retry-After when SLO-aware admission SHED
                # the request (predicted TTFT busts the objective).
                if q is not None:
                    err(429, "shed: predicted TTFT exceeds the declared "
                        "objective", "rate_limit_error",
                        headers={"Retry-After": str(int(-(-q // 1)))})
                else:
                    err(429, "queue full", "rate_limit_error",
                        headers={"Retry-After": "1"})
                return None
            return req, q, deadline_ms

        def _cancel_request(self, req) -> None:
            """Best-effort cancel from a handler thread: a hung tick may
            hold the lock forever — leaking the request is better than
            pinning this thread on acquire (the timeout is counted in
            server_lock_timeouts_total either way)."""
            if state.acquire_lock():
                try:
                    state.sched.cancel(req)
                finally:
                    state.lock.release()

        def _collect(self, req, q, matcher=None):
            """Drain q until the finish sentinel. Returns (tokens,
            aborted) — or None if the client vanished (cancelled, no
            response owed). `matcher` (StopSequenceMatcher) ends
            generation early when a stop sequence appears in the text."""
            toks = []
            while True:
                try:
                    tok = q.get(timeout=0.5)
                except queue.Empty:
                    if req.done or state.error:
                        break  # wedged/hung: answer with partials
                    if not self._client_alive():
                        self._cancel_request(req)
                        return None
                    continue
                if tok is None:
                    break
                toks.append(tok)
                if matcher is not None and not matcher.hit \
                        and not (req.stop_token >= 0
                                 and tok == req.stop_token):
                    matcher.feed(state.tok.decode([tok]))
                    if matcher.hit:
                        self._cancel_request(req)
            stop_hit = matcher is not None and matcher.hit
            aborted = (req.state == "cancelled" and not stop_hit) \
                or (state.error and not req.done)
            return toks, aborted

        def _handle_generate(self):
            try:
                body = self._read_body()
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            t0 = time.monotonic()
            admitted = self._admit(body)
            if admitted is None:
                return
            req, q, deadline_ms = admitted
            if body.get("stream"):
                self._stream(req, q, t0)
                return
            got = self._collect(req, q)
            if got is None:
                return
            toks, aborted = got
            if req.state == "expired":
                # the scheduler scrubbed/cancelled it at the deadline:
                # terminal 504 with where-it-died + elapsed detail
                self._deadline_504(req.expired_where or "running",
                                   deadline_ms, time.monotonic() - t0,
                                   openai=False, partial=toks)
                return
            if aborted:
                self._json(503, {"error": "generation aborted: "
                                 + (state.error or "cancelled"),
                                 "partial_tokens": toks})
                return
            self._json(200, {
                "tokens": toks,
                "text": state.tok.decode(toks),
                "ttft_s": req.ttft,
                "total_s": time.monotonic() - t0,
                # stop-token finish vs budget finish: the disaggregated
                # control plane's prefill leg (max_tokens=1) reads this
                # to know whether generation already ended — it cannot
                # infer the replica's default EOS id itself
                "stopped": bool(req.stop_token >= 0 and toks
                                and toks[-1] == req.stop_token),
            })

        def _handle_completions(self):
            """OpenAI-compatible /v1/completions (single choice)."""
            try:
                body = self._read_body()
                n_choices = int(body.get("n", 1))
                stops = body.get("stop") or []
                if isinstance(stops, str):
                    stops = [stops]
                if not (isinstance(stops, list)
                        and all(isinstance(s, str) for s in stops)):
                    raise ValueError("stop must be a string or a list "
                                     "of strings")
                if len(stops) > 4:
                    raise ValueError("at most 4 stop sequences")
            except (ValueError, TypeError) as e:
                self._json(400, {"error": {"message": str(e),
                                           "type": "invalid_request_error"}})
                return
            if n_choices != 1:
                self._json(400, {"error": {"message": "only n=1 supported",
                                           "type": "invalid_request_error"}})
                return
            admitted = self._admit(body, openai=True)
            if admitted is None:
                return
            req, q, deadline_ms = admitted
            matcher = StopSequenceMatcher(stops) if stops else None
            meta = {"id": f"cmpl-{req.id}", "object": "text_completion",
                    "created": int(time.time()), "model": state.model_name}
            t0 = time.monotonic()
            if body.get("stream"):
                self._stream_completions(req, q, meta, matcher)
                return
            got = self._collect(req, q, matcher)
            if got is None:
                return
            toks, aborted = got
            if req.state == "expired":
                self._deadline_504(req.expired_where or "running",
                                   deadline_ms, time.monotonic() - t0,
                                   openai=True)
                return
            if aborted:
                self._json(503, {"error": {
                    "message": "generation aborted: "
                               + (state.error or "cancelled"),
                    "type": "server_error"}})
                return
            token_stop = (req.stop_token >= 0 and toks
                          and toks[-1] == req.stop_token)
            if matcher is not None:
                # text comes from the matcher: everything before the
                # stop sequence (or everything fed, if none hit)
                matcher.flush()
                text = matcher.text[:matcher.released]
                finish = "stop" if (matcher.hit or token_stop) else "length"
            else:
                # OpenAI semantics: the stop marker is excluded from the
                # text (usage still counts it — it was generated)
                finish = "stop" if token_stop else "length"
                text = state.tok.decode(
                    toks[:-1] if token_stop else toks)
            self._json(200, {
                **meta,
                "choices": [{"text": text, "index": 0,
                             "logprobs": None, "finish_reason": finish}],
                "usage": {"prompt_tokens": len(req.prompt),
                          "completion_tokens": len(toks),
                          "total_tokens": len(req.prompt) + len(toks)},
            })

        def _client_alive(self) -> bool:
            """Peek the socket: a closed peer reads as EOF (b'')."""
            import socket
            try:
                data = self.connection.recv(1, socket.MSG_PEEK
                                            | socket.MSG_DONTWAIT)
                return data != b""
            except (BlockingIOError, InterruptedError):
                return True          # no data pending = still connected
            except OSError:
                return False

        def _sse(self, req, q, render_token, finish_payloads,
                 render_error, natural_cancel=lambda: False) -> None:
            """Shared SSE drain: headers, chunked framing, bounded-wait
            queue loop, wedge/cancel detection, disconnect cancel.

            render_token(tok) -> payload str or None (skip the chunk);
            finish_payloads(last_tok) -> payload strs on normal finish;
            render_error(msg) -> payload str for the abort event;
            natural_cancel() -> True when a handler-initiated cancel is
            a normal finish (stop-sequence hit), not an abort.
            """
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            if self._rid:
                self.send_header("X-Request-Id", self._rid)
            self.end_headers()

            def chunk(data: bytes) -> None:
                self.wfile.write(f"{len(data):X}\r\n".encode() + data
                                 + b"\r\n")

            try:
                last_tok = None
                while True:
                    try:
                        # bounded wait: a hung device must not pin this
                        # handler thread forever — bail once the request
                        # is drained OR the server wedged (a truly hung
                        # tick never delivers the sentinel)
                        tok = q.get(timeout=0.5)
                    except queue.Empty:
                        if req.done or state.error:
                            break
                        continue
                    if tok is None:
                        break
                    last_tok = tok
                    payload = render_token(tok)
                    if payload is not None:
                        chunk(f"data: {payload}\n\n".encode())
                if req.state == "expired":
                    # deadline fired mid-stream: terminal error event —
                    # already-streamed tokens stand, the client learns
                    # the stream died on its own latency budget
                    err = render_error("deadline exceeded "
                                       f"({req.expired_where or 'running'})")
                    chunk(f"data: {err}\n\n".encode())
                elif (req.state == "cancelled" and not natural_cancel()) \
                        or (state.error and not req.done):
                    err = render_error("generation aborted: "
                                       + (state.error or "cancelled"))
                    chunk(f"data: {err}\n\n".encode())
                else:
                    for payload in finish_payloads(last_tok):
                        chunk(f"data: {payload}\n\n".encode())
                chunk(b"")  # terminating chunk
            except (BrokenPipeError, ConnectionResetError):
                # client went away: stop generating for a dead socket
                self._cancel_request(req)

        def _stream(self, req, q, t0) -> None:
            self._sse(
                req, q,
                lambda tok: json.dumps({"token": tok,
                                        "text": state.tok.decode([tok])}),
                lambda last: ["[DONE]"],
                lambda msg: json.dumps({"error": msg}))

        def _stream_completions(self, req, q, meta, matcher=None) -> None:
            """SSE in the OpenAI streaming-chunk shape. With a stop-
            sequence matcher, only text provably before any stop
            sequence streams out (holdback), and a hit cancels the
            request as a NORMAL finish."""
            def content(text):
                return json.dumps({**meta, "choices": [
                    {"text": text, "index": 0, "logprobs": None,
                     "finish_reason": None}]})

            def render_token(tok):
                if req.stop_token >= 0 and tok == req.stop_token:
                    return None  # stop marker is excluded from the text
                piece = state.tok.decode([tok])
                if matcher is not None:
                    if matcher.hit:
                        return None  # tokens racing in after the hit
                    piece = matcher.feed(piece)
                    if matcher.hit:
                        self._cancel_request(req)
                    if not piece:
                        return None
                return content(piece)

            def finish_payloads(last_tok):
                msgs = []
                stop_hit = matcher is not None and matcher.hit
                if matcher is not None and not stop_hit:
                    tail = matcher.flush()
                    if tail:
                        msgs.append(content(tail))
                finish = "stop" if (stop_hit or (req.stop_token >= 0
                                                 and last_tok
                                                 == req.stop_token)) \
                    else "length"
                msgs.append(json.dumps({**meta, "choices": [
                    {"text": "", "index": 0, "logprobs": None,
                     "finish_reason": finish}]}))
                msgs.append("[DONE]")
                return msgs

            self._sse(req, q, render_token, finish_payloads,
                      lambda msg: json.dumps({"error": {
                          "message": msg, "type": "server_error"}}),
                      natural_cancel=lambda: (matcher is not None
                                              and matcher.hit))

    return Handler


def serve_forever(scheduler, tokenizer, host: str = "0.0.0.0",
                  port: int = 8000, max_queue: int = 256,
                  ready_event: Optional[threading.Event] = None,
                  heartbeat=None, model_name: str = "butterfly",
                  role: str = "both"):
    """Blocking serve loop. `ready_event` is set once listening (tests).

    `heartbeat`: a HeartbeatMonitor to use (callers may tune interval /
    misses / probe); defaults to the LOCAL device probe. Deliberately so
    even multi-host: an idle-timer collective probe would be issued in
    unsynchronized order across hosts and desync the SPMD program
    stream — on a pod each host watchdogs its own chip, and a dead PEER
    surfaces as the next real tick stalling on its collective, which
    the staleness latch catches.
    """
    from butterfly_tpu.obs.health import HeartbeatMonitor
    if heartbeat is None:
        heartbeat = HeartbeatMonitor()
    state = ServerState(scheduler, tokenizer, max_queue,
                        heartbeat=heartbeat, model_name=model_name,
                        role=role)
    state.thread.start()
    # stdlib default listen backlog is 5: a burst of concurrent clients
    # gets connection resets before the accept loop ever sees them
    # (observed at 50 simultaneous connects in the r5 soak). Size it
    # with the admission queue — excess load should get a 503/429 from
    # US, not a TCP reset from the kernel. Local subclass so the bump
    # stays per-server instead of mutating the shared stdlib class.
    class _Server(ThreadingHTTPServer):
        request_queue_size = max(128, max_queue)

    httpd = _Server((host, port), make_handler(state))
    state.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        state.stop.set()
        if state.heartbeat is not None:
            state.heartbeat.stop()
        httpd.server_close()
    return 0


def run_server(args) -> int:
    """`butterfly serve` entrypoint (serve/cli.py)."""
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.sched.scheduler import Scheduler
    from butterfly_tpu.serve.cli import build_mesh, load_params, resolve_model
    from butterfly_tpu.utils.tokenizer import load_tokenizer

    model = resolve_model(args)
    tok = load_tokenizer(args.tokenizer or args.ckpt)
    mesh = build_mesh(args)
    params = load_params(model, args)
    rt = RuntimeConfig(max_batch_size=args.max_batch,
                       max_seq_len=args.max_seq, page_size=args.page_size,
                       top_k=args.top_k, top_p=args.top_p,
                       max_queue=args.max_queue,
                       prefix_caching=getattr(args, "prefix_caching", False),
                       host_kv_tier_mb=getattr(args, "host_tier_mb", 0.0),
                       host_kv_tier_dir=getattr(args, "host_tier_dir", None),
                       kv_quant=getattr(args, "kv_quant", "none"),
                       speculative_gamma=getattr(args, "speculate", 0),
                       draft_model=getattr(args, "draft_source", "ngram"),
                       draft_layers=getattr(args, "draft_layers", 0),
                       draft_ckpt=getattr(args, "draft_ckpt", None),
                       spec_tree_width=getattr(args, "spec_tree", 0),
                       spec_tree_nodes=getattr(args, "spec_tree_nodes", 0),
                       decode_steps_per_tick=getattr(
                           args, "decode_steps_per_tick", 1),
                       prefill_max_batch=getattr(
                           args, "prefill_max_batch", 8),
                       inflight_blocks=getattr(
                           args, "inflight_blocks", 2),
                       seq_parallel_threshold=getattr(
                           args, "seq_parallel_threshold", 0),
                       seq_parallel_chunk=getattr(
                           args, "seq_parallel_chunk", 0))
    engine = ServingEngine(model, params, rt, mesh=mesh)
    # Tracing defaults ON for the serve entrypoint (/debug/requests is
    # the production debugging surface); --no-trace turns it off for
    # benchmarking the bare hot path.
    tracer = None
    if not getattr(args, "no_trace", False):
        from butterfly_tpu.obs.trace import Tracer
        tracer = Tracer()
    # Declared latency objectives (ms on the CLI, seconds internally):
    # the scheduler measures per-request attainment into the slo_*
    # counters and the rolling burn-rate gauge.
    slo_ttft = getattr(args, "slo_ttft_ms", None)
    slo_itl = getattr(args, "slo_itl_ms", None)
    # Anomaly flight recorder: always on for the serve entrypoint (one
    # bounded ring; events are per-admission/per-barrier, never
    # per-token). --flightrec-dir makes trigger artifacts land on disk
    # as JSON post-mortems; without it they are held in memory and
    # served at GET /debug/flightrecorder.
    from butterfly_tpu.obs.ticklog import FlightRecorder
    flightrec = FlightRecorder(
        dump_dir=getattr(args, "flightrec_dir", None))
    # Periodic signal-history recorder (GET /debug/timeseries): on by
    # default at 1 Hz — one bounded ring append per interval, zero per-
    # tick cost beyond a monotonic compare. --timeseries-interval 0
    # disables it entirely (timeseries=None: one is-None check/tick).
    # Its alert rules note structured `alert` events into the same
    # flight recorder, so threshold crossings land in post-mortems.
    ts_interval = getattr(args, "timeseries_interval", 1.0)
    timeseries = None
    if ts_interval and ts_interval > 0:
        from butterfly_tpu.obs.timeseries import (SignalRecorder,
                                                  default_rules)
        timeseries = SignalRecorder(interval_s=ts_interval,
                                    rules=default_rules(),
                                    flightrec=flightrec)
    sched = Scheduler(engine, tracer=tracer,
                      slo_ttft_s=slo_ttft / 1e3 if slo_ttft else None,
                      slo_itl_s=slo_itl / 1e3 if slo_itl else None,
                      flightrec=flightrec, timeseries=timeseries)
    # On-demand XProf server (--profiler-port): TensorBoard/XProf can
    # then trigger captures of the live process. Failure to start
    # (port in use, no profiler plugin) logs and serves without it —
    # POST /debug/profile still works either way.
    prof_port = getattr(args, "profiler_port", 0)
    if prof_port:
        from butterfly_tpu.obs.profile import start_profiler_server
        if start_profiler_server(prof_port):
            print(f"[butterfly] xprof profiler server on :{prof_port}",
                  flush=True)
    # Warm the serving programs (fresh-chunk prefill, warm-chunk
    # continuation, batched decode) before listening: the first user
    # doesn't pay 20-40s of XLA compile, and the heartbeat watchdog
    # never mistakes the startup compile for a dead device.
    print("[butterfly] warming serving programs...", flush=True)
    warm_len = min(2 * rt.prefill_chunk, rt.max_seq_len - 4)
    # a full gang of smallest-bucket prompts first (compiles the widest
    # [B, 16] batched-prefill program a burst will hit), then the long
    # chunked prompt (fresh + warm-continuation [1, T] buckets)
    gang = max(1, min(rt.prefill_max_batch, rt.max_batch_size))
    warms = [sched.submit([1], max_new_tokens=2) for _ in range(gang)]
    warms.append(sched.submit([1] * max(1, warm_len), max_new_tokens=2))
    sched.run_until_done()
    assert all(w.done for w in warms)
    mesh_desc = "" if mesh is None else \
        " mesh=" + "x".join(f"{k}{v}" for k, v in mesh.shape.items() if v > 1)
    print(f"[butterfly] serving {args.model} on {args.host}:{args.port} "
          f"(slots={rt.max_batch_size}, pages={engine.cache.num_pages - 1}"
          f"x{rt.page_size}tok{mesh_desc})", flush=True)
    return serve_forever(sched, tok, args.host, args.port,
                         max_queue=rt.max_queue, model_name=args.model,
                         role=getattr(args, "role", "both"))
