"""Host-RAM KV tier: chain-hash-addressed page store behind eviction.

The prefix cache (cache/prefix.py) recycles warm pages when the free
list runs dry — before this tier, recycling DROPPED the page contents,
so a cold chain's next admission paid its full prefill again. The tier
turns that drop into a demotion: the scheduler's evict hook reads the
page to the host (`engine.read_pages` — the same gather the
cross-replica export uses) and parks the bytes here, keyed by the very
chain digest the registry was keyed by. On the next prefix hit against
that digest the scheduler's reviver pulls the bytes back
(`engine.write_pages` into a freshly claimed page) and the admission
proceeds as a normal prefix-cache hit — the Mooncake-style second
cache tier, host DRAM under HBM.

Addressing is identical to fleet/kvtransfer.py — SHA-256 chain digests
over page-sized token blocks — so the tier also serves as an export
source: a decode replica asking /kv/pages for a chain this replica
evicted still gets the bytes (export_payload continues the leading run
from the tier when the device registry misses).

Capacity is byte-bounded with LRU demotion. An optional spill
directory turns the LRU drop into a disk demotion (one ``.npz`` per
page) so the tier degrades cold-to-disk instead of cold-to-gone;
spilled entries promote back to RAM on access. Correctness never
depends on the tier holding anything: a miss just means the admission
prefills the uncovered tail itself.

Thread-safe: one lock around the index. Device I/O never happens in
here — callers (the scheduler's hooks) read/write pages themselves and
hand this module host arrays only — so the lock never nests with the
serving lock's device work.

stdlib + numpy only.
"""
from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

#: (k, v, k_scale, v_scale) host arrays in the engine.read_pages
#: per-page layout: k/v [L, Kv, page, H]; scales [L, Kv*page] iff the
#: pool is int8, else None.
PageData = Tuple[np.ndarray, np.ndarray,
                 Optional[np.ndarray], Optional[np.ndarray]]


def _nbytes(data: PageData) -> int:
    return sum(a.nbytes for a in data if a is not None)


class HostKVTier:
    """Byte-bounded LRU store of evicted KV pages, chain-digest keyed."""

    def __init__(self, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        if capacity_bytes <= 0:
            raise ValueError("host KV tier needs a positive capacity")
        self.capacity_bytes = capacity_bytes
        self.spill_dir = spill_dir
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        # digest -> PageData (RAM-resident), LRU order: oldest first
        self._ram: "OrderedDict[bytes, PageData]" = OrderedDict()
        # digest -> .npz path (disk-resident); plain dict, no LRU — disk
        # is the terminal tier and is not capacity-managed here
        self._disk: Dict[bytes, str] = {}
        self.bytes_used = 0
        # monotonic stats the scheduler's kv_tier_* metrics read
        self.saves = 0       # pages parked (evict hook)
        self.restores = 0    # pages handed back (reviver / export)
        self.misses = 0      # lookups that found nothing anywhere
        self.spills = 0      # RAM -> disk demotions
        self.drops = 0       # pages lost at capacity (no spill dir)

    # -- internals (lock held) ----------------------------------------------

    def _spill_path(self, h: bytes) -> str:
        return os.path.join(self.spill_dir, h.hex() + ".npz")

    def _demote_oldest(self) -> None:
        h, data = self._ram.popitem(last=False)
        self.bytes_used -= _nbytes(data)
        if self.spill_dir is None:
            self.drops += 1
            return
        arrays = {"k": data[0], "v": data[1]}
        if data[2] is not None:
            arrays["k_scale"], arrays["v_scale"] = data[2], data[3]
        try:
            np.savez(self._spill_path(h), **arrays)
            self._disk[h] = self._spill_path(h)
            self.spills += 1
        except OSError:
            self.drops += 1  # disk full/unwritable: degrade to a drop

    def _load_spilled(self, h: bytes) -> Optional[PageData]:
        path = self._disk.get(h)
        if path is None:
            return None
        try:
            with np.load(path) as z:
                data = (z["k"], z["v"],
                        z["k_scale"] if "k_scale" in z else None,
                        z["v_scale"] if "v_scale" in z else None)
        except (OSError, KeyError, ValueError):
            del self._disk[h]  # corrupt/vanished spill: forget it
            return None
        return data

    # -- the tier surface ----------------------------------------------------

    def save(self, h: bytes, k: np.ndarray, v: np.ndarray,
             k_scale: Optional[np.ndarray] = None,
             v_scale: Optional[np.ndarray] = None) -> None:
        """Park one evicted page's host bytes under chain digest `h`.
        Arrays are copied (callers hand views into a larger gather);
        re-saving a digest refreshes its LRU position."""
        data: PageData = (
            np.array(k, copy=True), np.array(v, copy=True),
            None if k_scale is None else np.array(k_scale, copy=True),
            None if v_scale is None else np.array(v_scale, copy=True))
        with self._lock:
            old = self._ram.pop(h, None)
            if old is not None:
                self.bytes_used -= _nbytes(old)
            self._ram[h] = data
            self.bytes_used += _nbytes(data)
            self.saves += 1
            while self.bytes_used > self.capacity_bytes and \
                    len(self._ram) > 1:
                self._demote_oldest()

    def load(self, h: bytes) -> Optional[PageData]:
        """Page bytes for digest `h`, or None (a counted miss). A hit
        refreshes LRU position; a spilled entry promotes back to RAM."""
        with self._lock:
            data = self._ram.pop(h, None)
            if data is not None:
                self._ram[h] = data  # refresh: newest at the end
                self.restores += 1
                return data
            data = self._load_spilled(h)
            if data is None:
                self.misses += 1
                return None
            # promote to RAM: the copy here is authoritative again, so
            # the spill file goes away rather than rotting stale
            path = self._disk.pop(h)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._ram[h] = data
            self.bytes_used += _nbytes(data)
            while self.bytes_used > self.capacity_bytes and \
                    len(self._ram) > 1:
                self._demote_oldest()
            self.restores += 1
            return data

    def contains(self, h: bytes) -> bool:
        """Membership without touching LRU order or the stats."""
        with self._lock:
            return h in self._ram or h in self._disk

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "entries": float(len(self._ram)),
                "spilled_entries": float(len(self._disk)),
                "bytes": float(self.bytes_used),
                "capacity_bytes": float(self.capacity_bytes),
                "saves": float(self.saves),
                "restores": float(self.restores),
                "misses": float(self.misses),
                "spills": float(self.spills),
                "drops": float(self.drops),
            }
