"""Automatic prefix caching: content-addressed KV page reuse.

Realizes the shared-prompt optimization for the paged KV cache
(SURVEY.md §2.2 C5/C6; the reference is an unimplemented scaffold —
SURVEY.md §0 — so the semantics follow the public vLLM "automatic
prefix caching" design, re-done for the TPU serving stack here):

* Every FULL page of a finished/running sequence is registered in a
  host-side registry keyed by a rolling content hash over the token
  chain (page i's key commits to all tokens of pages 0..i, so a hash
  hit implies the whole prefix matches).
* Admission walks the new request's prompt page-by-page through the
  registry; matched pages are attached to the slot read-only (the
  request's first private page starts after them) and their tokens are
  skipped entirely — the engine's warm-prefill path continues from
  `start = cached_tokens` against K/V that is already in HBM.
* Pages are refcounted. A registered page with refcount 0 stays warm
  in an LRU "evictable" list and is only recycled when the free list
  runs dry, so `free_pages` counts it as available; a hit on an
  evictable page revives it at zero cost.

Device-side invariant that makes read-only sharing safe: writes land
at absolute positions >= the writer's `start`, and a matched prefix is
always a whole number of pages, so a sharing slot never scatters into
a shared page (its first write position opens its first private page).
The match is additionally capped at len(tokens)-1 so at least one real
token remains to produce last-token logits.

Interface-compatible with cache.allocator.PageAllocator (grow/release/
pages_of/can_grow/free_pages) plus `admit` and `register`; the
scheduler talks to either through the same calls.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Set

from butterfly_tpu.cache.allocator import PageAllocator


def chain_block_hashes(tokens: List[int], page_size: int,
                       max_pages: Optional[int] = None) -> List[bytes]:
    """SHA-256 chain digests, one per FULL page-sized block of `tokens`.

    Block i's digest commits to all tokens of blocks 0..i, so equality of
    digest i implies the whole leading prefix matches. Cryptographic, NOT
    Python hash(): token ids are client-controlled (/generate accepts raw
    id lists), and a constructible collision would silently alias another
    prefix — in the allocator that means attaching another request's K/V
    pages (cross-request output leakage), in the router it means
    steerable affinity placement.

    Shared by PrefixCachingAllocator (page registry keys) and
    router/policy.py (prefix-affinity routing keys): both layers hashing
    the same blocks the same way is what makes router affinity line up
    with where cached pages actually live.
    """
    ps = page_size
    n = len(tokens) // ps
    if max_pages is not None:
        n = min(n, max_pages)
    hashes: List[bytes] = []
    h = b""
    for i in range(n):
        m = hashlib.sha256(h)
        m.update(b",".join(b"%d" % t for t in tokens[i * ps:(i + 1) * ps]))
        h = m.digest()
        hashes.append(h)
    return hashes


class PrefixCachingAllocator(PageAllocator):
    """PageAllocator plus content-hash prefix reuse.

    Inherits the free-list bookkeeping and query surface (pages_of /
    pages_needed / can_grow — the latter reads the overridden
    `free_pages`, which counts warm evictable pages as available);
    overrides the mutation surface for refcounts and LRU eviction.
    """

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        super().__init__(num_pages, page_size, max_pages_per_seq)
        self._slot_ref: Dict[int, Set[int]] = {}  # slot -> refcounted subset
        self._entries: Dict[bytes, int] = {}      # chain digest -> page id
        self._page_hash: Dict[int, bytes] = {}    # page id -> chain digest
        self._ref: Dict[int, int] = {}            # page id -> refcount
        self._evictable: "OrderedDict[int, None]" = OrderedDict()  # LRU
        self.hit_tokens = 0      # stats: prompt tokens served from cache
        self.lookup_tokens = 0   # stats: prompt tokens looked up
        # Host-tier hooks (cache/hosttier.py wiring), opt-in with the
        # scheduler's attribute-is-None contract:
        # * on_evict(chain_digest, page_id) fires as a registered page
        #   is recycled, AFTER deregistration and BEFORE the page id
        #   returns to the free list — the one moment the device bytes
        #   are both stable (registered pages are content-immutable)
        #   and about to be lost. The hook must not re-enter this
        #   allocator; failures are swallowed (the tier is best-effort
        #   — losing a demotion costs a future prefill, never
        #   correctness).
        # * reviver(chain_digest) -> page_id|None fires on a registry
        #   miss during admission's prefix walk: a tier hit claims a
        #   page via import_page, lands the bytes, and returns the page
        #   id so the walk continues as if the page had stayed warm.
        self.on_evict = None
        self.reviver = None

    # -- queries ------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        """Pages available right now: truly free + warm-but-unreferenced."""
        return len(self._free) + len(self._evictable)

    # -- registry internals --------------------------------------------------

    def _chain_hashes(self, tokens: List[int], max_pages: int) -> List[bytes]:
        """Registry keys: the shared chain_block_hashes at page size."""
        return chain_block_hashes(tokens, self.page_size, max_pages)

    def _evict_one(self) -> None:
        pid, _ = self._evictable.popitem(last=False)  # oldest first
        h = self._page_hash.pop(pid)
        del self._entries[h]
        del self._ref[pid]
        if self.on_evict is not None:
            try:
                self.on_evict(h, pid)
            except Exception:
                pass  # demotion is best-effort; eviction must proceed
        self._free.append(pid)

    def _take_free(self) -> int:
        if not self._free:
            self._evict_one()
        return self._free.pop()

    def _incref(self, pid: int) -> None:
        self._ref[pid] += 1
        self._evictable.pop(pid, None)

    def _decref(self, pid: int) -> None:
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            self._evictable[pid] = None  # newest at the end

    # -- mutations ----------------------------------------------------------

    def admit(self, slot: int, tokens: List[int],
              need_len: int) -> Optional[int]:
        """Attach the longest registered prefix of `tokens` to the fresh
        slot, then allocate private pages through `need_len` tokens.
        Returns the number of prompt tokens already in cache (0 if no
        hit), or None if the request cannot fit (nothing is allocated).
        """
        assert slot not in self._owned, "admit() requires an empty slot"
        if need_len > self.max_pages_per_seq * self.page_size:
            return None
        ps = self.page_size
        # cap: leave >= 1 token to prefill so last-token logits exist
        matchable = (len(tokens) - 1) // ps
        matched: List[int] = []
        for h in self._chain_hashes(tokens, matchable):
            pid = self._entries.get(h)
            if pid is None and self.reviver is not None:
                # registry miss: give the host tier a chance to revive
                # the chain's next page (import_page + a device write on
                # the scheduler side). The revive may itself evict — the
                # inline incref below is what keeps THIS chain's earlier
                # matches off the evictable list while that happens.
                pid = self.reviver(h)
            if pid is None:
                break
            # incref BEFORE counting availability: a matched page may
            # sit in the evictable list, and it must count as held, not
            # as free.
            self._incref(pid)
            matched.append(pid)
        want = -(-need_len // ps) - len(matched)
        if want > len(self._free) + len(self._evictable):
            for pid in matched:  # rollback, nothing allocated
                self._decref(pid)
            return None
        # stats only for admissions that actually happen: the scheduler
        # retries a refused head-of-queue request every tick, and those
        # retries must not inflate the hit rate
        self.lookup_tokens += len(tokens)
        self.hit_tokens += len(matched) * ps
        self._owned[slot] = list(matched)
        self._slot_ref[slot] = set(matched)
        fresh = [self._take_free() for _ in range(max(0, want))]
        self._owned[slot].extend(fresh)
        return len(matched) * ps

    def register(self, slot: int, tokens: List[int]) -> int:
        """Publish `slot`'s full pages holding `tokens` into the registry
        so future admissions can share them. `tokens` must be exactly the
        tokens whose K/V the device has written for this slot (callers
        pass the written prefix, which can trail all_tokens by one: the
        latest sampled token's K/V lands on the *next* decode step).
        Returns the number of newly registered pages."""
        pages = self._owned.get(slot, ())
        refset = self._slot_ref.setdefault(slot, set())
        new = 0
        for i, h in enumerate(self._chain_hashes(tokens, len(pages))):
            pid = pages[i]
            if pid in refset:
                continue  # already shared/registered under this chain
            if h in self._entries or pid in self._page_hash:
                # content already cached via another page (duplicate
                # prompt completed concurrently) — keep the existing
                # entry; this slot's copy stays private
                continue
            self._entries[h] = pid
            self._page_hash[pid] = h
            self._ref[pid] = 1  # the slot's own reference
            refset.add(pid)
            new += 1
        return new

    def release(self, slot: int) -> List[int]:
        """Return `slot`'s pages: refcounted ones are decref'd (staying
        warm for future hits), private ones go back to the free list."""
        pages = self._owned.pop(slot, [])
        refset = self._slot_ref.pop(slot, set())
        freed = []
        for pid in reversed(pages):
            if pid in refset:
                self._decref(pid)
            else:
                self._free.append(pid)
                freed.append(pid)
        return freed

    # -- cross-replica transfer (fleet/kvtransfer.py) ------------------------

    def lookup(self, h: bytes) -> Optional[int]:
        """Registered page id for a chain digest, or None. The export
        path resolves the requester's hash chain page-by-page; the
        leading matched run is what ships (pages after a gap could
        never be attached by `admit`, which stops at the first miss)."""
        return self._entries.get(h)

    def pin(self, pids: List[int]) -> None:
        """Hold pages against eviction/recycling while their contents
        are read out for a cross-replica transfer. Refcount-based, so a
        pinned warm page leaves the evictable list exactly like a page
        attached to a slot; callers MUST unpin in a finally block —
        transfer pins are transient and are not slot holders, so
        check_invariants only balances once they are released."""
        for pid in pids:
            self._incref(pid)

    def unpin(self, pids: List[int]) -> None:
        for pid in pids:
            self._decref(pid)

    def import_page(self, h: bytes) -> Optional[int]:
        """Claim a page for externally produced K/V content keyed by
        chain digest `h` and register it warm (refcount 0, evictable —
        exactly the state a released registered page sits in, so a
        later `admit` revives it as a normal prefix hit). Returns the
        page id the caller must now write the K/V bytes into, None if
        the digest is already cached (idempotent re-import), and raises
        MemoryError when every page is held by a live slot. Import in
        chain order: on MemoryError the pages already landed form a
        leading run, which is the only shape `admit` can use."""
        if h in self._entries:
            return None
        if not self._free and not self._evictable:
            raise MemoryError("no free or evictable pages for KV import")
        pid = self._take_free()
        self._entries[h] = pid
        self._page_hash[pid] = h
        self._ref[pid] = 0
        self._evictable[pid] = None
        return pid

    # -- diagnostics ---------------------------------------------------------

    def check_invariants(self) -> None:
        """Every page is in exactly one place; refcounts match holders."""
        seen: Dict[int, str] = {}

        def claim(pid, where):
            assert pid not in seen or (
                where == "shared" and seen[pid] == "shared"), \
                f"page {pid} in {seen.get(pid)} and {where}"
            seen[pid] = where

        for pid in self._free:
            claim(pid, "free")
        counts: Dict[int, int] = {}
        for slot, pages in self._owned.items():
            refset = self._slot_ref.get(slot, set())
            for pid in pages:
                if pid in refset:
                    claim(pid, "shared")
                    counts[pid] = counts.get(pid, 0) + 1
                else:
                    claim(pid, "private")
        for pid in self._evictable:
            claim(pid, "shared")
        for pid, rc in self._ref.items():
            assert rc == counts.get(pid, 0), \
                f"page {pid} refcount {rc} != holders {counts.get(pid, 0)}"
            assert (rc == 0) == (pid in self._evictable)
            assert pid in self._page_hash
        assert len(seen) == self.num_pages, \
            f"{len(seen)} pages accounted, expected {self.num_pages}"
