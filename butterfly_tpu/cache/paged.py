"""Paged KV cache: block-table layout for continuous batching.

Realizes BASELINE.json configs[4] ("continuous batching + paged KV cache");
the reference has no implementation (SURVEY.md §0). Design (vLLM-style
semantics, TPU-native mechanics):

* One global page pool per layer stack: k/v_pages [L, P, Kv, page, H] in
  HBM. Sequences own pages through a block table [slots, max_pages] of
  page ids; page P-1 is reserved as the null page (block tables are
  initialized to it, so gathers from unallocated slots read zeros and the
  causal mask hides them).
* The dim order puts (page, H) minor: TPU tiles pad the two minor dims
  ((16,128) bf16, (32,128) int8), so a Kv-minor layout would inflate
  physical HBM 2-4x for GQA models (Kv=8 pads to the sublane tile); with
  page_size >= the sublane tile there is no padding at all, and each
  (kv, page) read is one contiguous [page, H] tile run.
* int8 mode (RuntimeConfig.kv_quant="int8"): k/v_pages hold int8 codes
  and k/v_scale_pages [L, P, Kv*page] hold one f32 scale per stored
  vector (absmax over head_dim / 127 — models.common.quantize_kv). The
  scale dim is FLATTENED kv-major: (a) the page-granular decode kernel
  streams it as one lane-aligned [Kv*page] row per page (a 2-D [Kv,page]
  block would need a sublane->lane relayout in-kernel), and (b) a
  `tensor`-axis shard of the Kv dim is a contiguous chunk of the flat
  dim (chunk = (Kv/tp)*page), so the same PartitionSpec machinery
  shards codes and scales consistently. Decode streams half the cache
  bytes from HBM; dequantization fuses into the attention dots (K scale
  applied to scores output-side, V scale folded into the probs), so no
  bf16 copy of the pool ever materializes.
* Token writes are scatters (`.at[...].set`) at (page_table[slot, t//page],
  t%page) — XLA Scatter keeps the pool HBM-resident, the paged analogue of
  the contiguous cache's DynamicUpdateSlice.
* Attention reads gather each slot's pages back into a contiguous
  [B, S_max, ...] view per layer (XLA Gather). This reference path reads
  the same bytes a contiguous cache would; the Pallas paged-attention
  kernel (ops/) replaces gather+attend for decode so only *used* pages are
  touched.
* Page allocation/free is host-side (cache/allocator.py) — the device
  never sees dynamic shapes, only a static pool and int32 tables.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from butterfly_tpu.core.config import ModelConfig, RuntimeConfig


class PagedKVCache(NamedTuple):
    k_pages: jax.Array     # [L, P, Kv, page, H] (int8 codes when quantized)
    v_pages: jax.Array     # [L, P, Kv, page, H]
    page_table: jax.Array  # [slots, max_pages] int32, null = P-1
    lengths: jax.Array     # [slots] int32 tokens written per slot
    k_scale_pages: Optional[jax.Array] = None  # [L, P, Kv*page] f32 iff int8
    v_scale_pages: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def null_page(self) -> int:
        return self.k_pages.shape[1] - 1

    @property
    def max_seq(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def num_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale_pages is not None


def init_paged_cache(cfg: ModelConfig, runtime: RuntimeConfig,
                     dtype: Optional[jnp.dtype] = None) -> PagedKVCache:
    """Pool sized from the runtime config (+1 reserved null page).

    runtime.kv_quant="int8" allocates int8 code pools + f32 scale pools
    (the serving-path twin of models.common.init_cache(quant="int8"))."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    page = runtime.page_size
    max_pages = -(-runtime.max_seq_len // page)
    P = runtime.num_pages or runtime.max_batch_size * max_pages
    P += 1  # null page
    shape = (cfg.num_layers, P, cfg.num_kv_heads, page, cfg.head_dim)
    table = jnp.full((runtime.max_batch_size, max_pages), P - 1, jnp.int32)
    lengths = jnp.zeros((runtime.max_batch_size,), jnp.int32)
    if runtime.kv_quant == "int8":
        sshape = (cfg.num_layers, P, cfg.num_kv_heads * page)
        return PagedKVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros(shape, jnp.int8),
            page_table=table, lengths=lengths,
            k_scale_pages=jnp.zeros(sshape, jnp.float32),
            v_scale_pages=jnp.zeros(sshape, jnp.float32),
        )
    if runtime.kv_quant != "none":
        raise ValueError(f"unknown kv quant {runtime.kv_quant!r}")
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=table, lengths=lengths,
    )


def write_paged_layer(k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array, k: jax.Array, v: jax.Array,
                      start: jax.Array,
                      active: Optional[jax.Array] = None,
                      k_scale_pages: Optional[jax.Array] = None,
                      v_scale_pages: Optional[jax.Array] = None):
    """Scatter new tokens into one layer's page pool.

    k_pages/v_pages: [P, Kv, page, H]; k/v: [B, T, Kv, H] (T new tokens per
    slot); start: [B] first absolute position of each slot's new tokens.
    Inactive slots' writes are redirected to the null page. Positions past
    a slot's allocated pages must not occur for active slots (the host
    allocator guarantees capacity before scheduling the step).

    Quantized pools (int8 codes + scale pools [P, Kv*page]): k/v arrive
    as floats and are quantized per-vector on the way in. Returns
    (k_pages, v_pages, k_scale_pages, v_scale_pages) — scales None when
    the pool is float.
    """
    Pp, Kv, page, H = k_pages.shape
    B, T = k.shape[0], k.shape[1]
    pos = start[:, None] + jnp.arange(T)[None, :]          # [B,T] absolute
    page_idx = jnp.take_along_axis(page_table, pos // page, axis=1)  # [B,T]
    # Prefill buckets pad T past the true prompt, so pos can exceed the
    # table row's capacity. Route those positions to the null page
    # explicitly rather than relying on take_along_axis's out-of-bounds
    # fill (INT32_MIN) being dropped by the scatter below.
    page_idx = jnp.where(pos < page_table.shape[1] * page, page_idx, Pp - 1)
    if active is not None:
        page_idx = jnp.where(active[:, None], page_idx, Pp - 1)
    offset = pos % page                                     # [B,T]
    flat_pages = page_idx.reshape(-1)
    flat_off = offset.reshape(-1)
    if k_scale_pages is not None:
        from butterfly_tpu.models.common import quantize_kv
        kq, ks = quantize_kv(k)   # codes [B,T,Kv,H], scales [B,T,Kv]
        vq, vs = quantize_kv(v)
        k_pages = k_pages.at[flat_pages, :, flat_off].set(
            kq.reshape(B * T, Kv, H))
        v_pages = v_pages.at[flat_pages, :, flat_off].set(
            vq.reshape(B * T, Kv, H))
        # flat scale dim is kv-major: col = kv*page + offset
        cols = jnp.arange(Kv)[None, :] * page + flat_off[:, None]  # [BT,Kv]
        k_scale_pages = k_scale_pages.at[flat_pages[:, None], cols].set(
            ks.reshape(B * T, Kv))
        v_scale_pages = v_scale_pages.at[flat_pages[:, None], cols].set(
            vs.reshape(B * T, Kv))
        return k_pages, v_pages, k_scale_pages, v_scale_pages
    kf = k.reshape(B * T, Kv, H).astype(k_pages.dtype)
    vf = v.reshape(B * T, Kv, H).astype(v_pages.dtype)
    k_pages = k_pages.at[flat_pages, :, flat_off].set(kf)
    v_pages = v_pages.at[flat_pages, :, flat_off].set(vf)
    return k_pages, v_pages, None, None


def gather_paged_layer(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """One layer's pages -> contiguous [B, S_max, Kv, H] view (XLA Gather)."""
    Pp, Kv, page, H = pages.shape
    B, max_pages = page_table.shape
    out = pages[page_table]                 # [B, max_pages, Kv, page, H]
    out = out.transpose(0, 1, 3, 2, 4)      # [B, max_pages, page, Kv, H]
    return out.reshape(B, max_pages * page, Kv, H)


def gather_paged_layer_q(pages: jax.Array, scale_pages: jax.Array,
                         page_table: jax.Array):
    """Quantized gather: codes [B, Kv, S, H] + scales [B, Kv, S] — the
    kv-major order models.common.attend expects for int8 caches."""
    Pp, Kv, page, H = pages.shape
    B, max_pages = page_table.shape
    codes = pages[page_table]               # [B, mp, Kv, page, H]
    codes = codes.transpose(0, 2, 1, 3, 4).reshape(B, Kv, max_pages * page, H)
    sc = scale_pages[page_table]            # [B, mp, Kv*page]
    sc = sc.reshape(B, max_pages, Kv, page).transpose(0, 2, 1, 3)
    return codes, sc.reshape(B, Kv, max_pages * page)


# ---------------------------------------------------------------------------
# Paged forward pass (reference path; Pallas decode kernel lives in ops/)
# ---------------------------------------------------------------------------

def paged_layer_body(x, lp, kp, vp, *, cfg: ModelConfig, page_table,
                     positions, mask, cos, sin, active, use_kernel: bool,
                     fresh: bool, ksp=None, vsp=None):
    """One transformer layer against one layer's page pool slice.

    Shared by paged_forward's full-stack scan and the stage-local scan of
    the pipeline serving path (parallel/pipeline.py) so the two cannot
    drift. x: [B,T,D]; kp/vp: [P,Kv,page,H]; ksp/vsp: [P,Kv*page] scale
    slices iff the pool is int8. Returns (x, kp, vp[, ksp, vsp]).
    """
    from butterfly_tpu.models.common import (
        _cast_float, attend, attn_output, ffn_block, pre_norm, qkv_proj)

    T = x.shape[1]
    quant = ksp is not None
    compute_dtype = jnp.dtype(cfg.dtype)
    lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
    start = positions[:, 0]

    h = pre_norm(x, lp["ln1"], cfg)
    q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
    kp, vp, ksp, vsp = write_paged_layer(kp, vp, page_table, k, v, start,
                                         active, ksp, vsp)
    out = None
    if use_kernel and T == 1:
        from butterfly_tpu.ops.paged_attention import paged_attention_sharded
        # lengths INCLUDING the token just written (inactive: 0 -> no
        # pages visited, output discarded)
        lens = jnp.where(active, positions[:, 0] + 1, 0)
        out = paged_attention_sharded(q[:, 0], kp, vp, page_table, lens,
                                      ksp, vsp)
        out = out[:, None] if out is not None else None
    elif cfg.attn_impl == "flash" and T > 1 and fresh:
        from butterfly_tpu.ops.flash_attention import flash_attention_sharded
        # fresh prefill attends over the just-projected bf16 K/V, so the
        # kernel path is identical for int8 pools
        out = flash_attention_sharded(q, k, v, causal=True)
    if out is None:
        # no mesh axis can shard the kernel operands (or kernels off):
        # dense gather attention, which GSPMD partitions itself.
        if quant:
            ck, k_s = gather_paged_layer_q(kp, ksp, page_table)
            cv, v_s = gather_paged_layer_q(vp, vsp, page_table)
            out = attend(q, ck, cv, mask, cfg, k_s, v_s)
        else:
            ck = gather_paged_layer(kp, page_table)
            cv = gather_paged_layer(vp, page_table)
            out = attend(q, ck, cv, mask, cfg)
    x = x + attn_output(out, lp["attn"], cfg)
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    if quant:
        return x, kp, vp, ksp, vsp
    return x, kp, vp


def paged_forward(params, cfg: ModelConfig, tokens: jax.Array,
                  cache: PagedKVCache,
                  positions: Optional[jax.Array] = None,
                  active: Optional[jax.Array] = None,
                  use_kernel: bool = False,
                  fresh: bool = False,
                  last_index: Optional[jax.Array] = None):
    """Forward over [B,T] tokens against the paged cache.

    B must equal cache.num_slots (serving: one row per slot). `active`
    [B] bool masks slots with no live request: their lengths don't
    advance and their writes land on pages only they own (admission wrote
    their table), so garbage never leaks across requests. Returns
    (logits [B,T,V], updated cache).

    use_kernel: decode steps (T==1) attend through the Pallas paged-
    attention kernel — touches only each slot's live pages instead of
    gathering the full S_max view. Prefills (T>1) honor cfg.attn_impl
    ("flash" = Pallas blockwise kernel over the fresh K/V).

    last_index [B]: run the LM head only on each row's hidden state at
    that index — logits come back [B,1,V] (models.common.forward docs:
    the full-T head dominates prefill memory at LLM vocab sizes).
    """
    from butterfly_tpu.models.common import embed_tokens, final_logits, make_mask

    B, T = tokens.shape
    quant = cache.quantized
    if positions is None:
        positions = cache.lengths[:, None] + jnp.arange(T)[None, :]
    if active is None:
        active = jnp.ones((B,), bool)

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)
    mask = mask & active[:, None, None]

    def body(x, scanned):
        lp, kp, vp, *scales = scanned
        out = paged_layer_body(
            x, lp, kp, vp, cfg=cfg, page_table=cache.page_table,
            positions=positions, mask=mask, cos=cos, sin=sin, active=active,
            use_kernel=use_kernel, fresh=fresh,
            ksp=scales[0] if scales else None,
            vsp=scales[1] if scales else None)
        return out[0], tuple(out[1:])

    xs = (params["layers"], cache.k_pages, cache.v_pages)
    if quant:
        xs = xs + (cache.k_scale_pages, cache.v_scale_pages)
    x, new_pools = lax.scan(body, x, xs)
    if last_index is not None:
        x = jnp.take_along_axis(
            x, last_index[:, None, None].astype(jnp.int32), axis=1)
    logits = final_logits(params, cfg, x)
    new_len = jnp.where(active, cache.lengths + T, cache.lengths)
    return logits, PagedKVCache(new_pools[0], new_pools[1],
                                cache.page_table, new_len, *new_pools[2:])
