"""Paged KV cache: block-table layout for continuous batching.

Realizes BASELINE.json configs[4] ("continuous batching + paged KV cache");
the reference has no implementation (SURVEY.md §0). Design (vLLM-style
semantics, TPU-native mechanics):

* One global page pool per layer stack: k/v_pages [L, P, Kv, page, H] in
  HBM. Sequences own pages through a block table [slots, max_pages] of
  page ids; page P-1 is reserved as the null page (block tables are
  initialized to it, so gathers from unallocated slots read zeros and the
  causal mask hides them).
* The dim order puts (page, H) minor: TPU tiles pad the two minor dims
  ((16,128) bf16, (32,128) int8), so a Kv-minor layout would inflate
  physical HBM 2-4x for GQA models (Kv=8 pads to the sublane tile); with
  page_size >= the sublane tile there is no padding at all, and each
  (kv, page) read is one contiguous [page, H] tile run.
* int8 mode (RuntimeConfig.kv_quant="int8"): k/v_pages hold int8 codes
  and k/v_scale_pages [L, P, Kv*page] hold one f32 scale per stored
  vector (absmax over head_dim / 127 — models.common.quantize_kv). The
  scale dim is FLATTENED kv-major: (a) the page-granular decode kernel
  streams it as one lane-aligned [Kv*page] row per page (a 2-D [Kv,page]
  block would need a sublane->lane relayout in-kernel), and (b) a
  `tensor`-axis shard of the Kv dim is a contiguous chunk of the flat
  dim (chunk = (Kv/tp)*page), so the same PartitionSpec machinery
  shards codes and scales consistently. Decode streams half the cache
  bytes from HBM; dequantization fuses into the attention dots (K scale
  applied to scores output-side, V scale folded into the probs), so no
  bf16 copy of the pool ever materializes.
* Token writes are scatters (`.at[...].set`) at (page_table[slot, t//page],
  t%page) — XLA Scatter keeps the pool HBM-resident, the paged analogue of
  the contiguous cache's DynamicUpdateSlice.
* Attention reads gather each slot's pages back into a contiguous
  [B, S_max, ...] view per layer (XLA Gather). This reference path reads
  the same bytes a contiguous cache would; the Pallas paged-attention
  kernel (ops/) replaces gather+attend for decode so only *used* pages are
  touched.
* Page allocation/free is host-side (cache/allocator.py) — the device
  never sees dynamic shapes, only a static pool and int32 tables.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from butterfly_tpu.core.config import ModelConfig, RuntimeConfig
# Module-level, deliberately: these all run INSIDE traced code (every
# decode/prefill/spec dispatch), and a lazy in-function import executes
# on every trace — the same class of hot-path tax PR 3's _apply_top_k
# hoist removed (ISSUE 13 satellite: the remaining paged_layer_body /
# paged_forward in-function imports hoisted alongside the new warm-flash
# call). No cycle: models.common imports core.config, quant.int8, and
# ops.flash_attention, none of which import this module; the ops kernel
# wrappers import nothing project-local at module level.
from butterfly_tpu.models.common import (
    _cast_float, attend, attn_output, embed_tokens, ffn_block,
    final_logits, make_mask, pre_norm, qkv_proj, quantize_kv)
from butterfly_tpu.ops.flash_attention import flash_attention_sharded
from butterfly_tpu.ops.paged_attention import paged_attention_sharded


class PagedKVCache(NamedTuple):
    k_pages: jax.Array     # [L, P, Kv, page, H] (int8 codes when quantized)
    v_pages: jax.Array     # [L, P, Kv, page, H]
    page_table: jax.Array  # [slots, max_pages] int32, null = P-1
    lengths: jax.Array     # [slots] int32 tokens written per slot
    k_scale_pages: Optional[jax.Array] = None  # [L, P, Kv*page] f32 iff int8
    v_scale_pages: Optional[jax.Array] = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[3]

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def null_page(self) -> int:
        return self.k_pages.shape[1] - 1

    @property
    def max_seq(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def num_slots(self) -> int:
        return self.page_table.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale_pages is not None


def init_paged_cache(cfg: ModelConfig, runtime: RuntimeConfig,
                     dtype: Optional[jnp.dtype] = None) -> PagedKVCache:
    """Pool sized from the runtime config (+1 reserved null page).

    runtime.kv_quant="int8" allocates int8 code pools + f32 scale pools
    (the serving-path twin of models.common.init_cache(quant="int8"))."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    page = runtime.page_size
    max_pages = -(-runtime.max_seq_len // page)
    P = runtime.num_pages or runtime.max_batch_size * max_pages
    P += 1  # null page
    shape = (cfg.num_layers, P, cfg.num_kv_heads, page, cfg.head_dim)
    table = jnp.full((runtime.max_batch_size, max_pages), P - 1, jnp.int32)
    lengths = jnp.zeros((runtime.max_batch_size,), jnp.int32)
    if runtime.kv_quant == "int8":
        sshape = (cfg.num_layers, P, cfg.num_kv_heads * page)
        return PagedKVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros(shape, jnp.int8),
            page_table=table, lengths=lengths,
            k_scale_pages=jnp.zeros(sshape, jnp.float32),
            v_scale_pages=jnp.zeros(sshape, jnp.float32),
        )
    if runtime.kv_quant != "none":
        raise ValueError(f"unknown kv quant {runtime.kv_quant!r}")
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=table, lengths=lengths,
    )


def write_paged_layer(k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array, k: jax.Array, v: jax.Array,
                      start: jax.Array,
                      active: Optional[jax.Array] = None,
                      k_scale_pages: Optional[jax.Array] = None,
                      v_scale_pages: Optional[jax.Array] = None):
    """Scatter new tokens into one layer's page pool.

    k_pages/v_pages: [P, Kv, page, H]; k/v: [B, T, Kv, H] (T new tokens per
    slot); start: [B] first absolute position of each slot's new tokens.
    Inactive slots' writes are redirected to the null page. Positions past
    a slot's allocated pages must not occur for active slots (the host
    allocator guarantees capacity before scheduling the step).

    Quantized pools (int8 codes + scale pools [P, Kv*page]): k/v arrive
    as floats and are quantized per-vector on the way in. Returns
    (k_pages, v_pages, k_scale_pages, v_scale_pages) — scales None when
    the pool is float.

    Mixed-dispatch contract (ISSUE 18): inside a fused mixed block the
    per-slot `start` is the slot's live cursor/length carry and T is
    the chunk width C — a decode-phase lane writes its one token at
    start=length with the chunk tail masked inactive, a prefill-phase
    lane writes its next C prompt tokens at start=cursor. Both reduce
    to exactly this scatter; no new write primitive exists for the
    fused path, which is why fused and alternating pools are
    bit-identical.
    """
    Pp, Kv, page, H = k_pages.shape
    B, T = k.shape[0], k.shape[1]
    pos = start[:, None] + jnp.arange(T)[None, :]          # [B,T] absolute
    page_idx = jnp.take_along_axis(page_table, pos // page, axis=1)  # [B,T]
    # Prefill buckets pad T past the true prompt, so pos can exceed the
    # table row's capacity. Route those positions to the null page
    # explicitly rather than relying on take_along_axis's out-of-bounds
    # fill (INT32_MIN) being dropped by the scatter below.
    page_idx = jnp.where(pos < page_table.shape[1] * page, page_idx, Pp - 1)
    if active is not None:
        page_idx = jnp.where(active[:, None], page_idx, Pp - 1)
    offset = pos % page                                     # [B,T]
    flat_pages = page_idx.reshape(-1)
    flat_off = offset.reshape(-1)
    if k_scale_pages is not None:
        kq, ks = quantize_kv(k)   # codes [B,T,Kv,H], scales [B,T,Kv]
        vq, vs = quantize_kv(v)
        k_pages = k_pages.at[flat_pages, :, flat_off].set(
            kq.reshape(B * T, Kv, H))
        v_pages = v_pages.at[flat_pages, :, flat_off].set(
            vq.reshape(B * T, Kv, H))
        # flat scale dim is kv-major: col = kv*page + offset
        cols = jnp.arange(Kv)[None, :] * page + flat_off[:, None]  # [BT,Kv]
        k_scale_pages = k_scale_pages.at[flat_pages[:, None], cols].set(
            ks.reshape(B * T, Kv))
        v_scale_pages = v_scale_pages.at[flat_pages[:, None], cols].set(
            vs.reshape(B * T, Kv))
        return k_pages, v_pages, k_scale_pages, v_scale_pages
    kf = k.reshape(B * T, Kv, H).astype(k_pages.dtype)
    vf = v.reshape(B * T, Kv, H).astype(v_pages.dtype)
    k_pages = k_pages.at[flat_pages, :, flat_off].set(kf)
    v_pages = v_pages.at[flat_pages, :, flat_off].set(vf)
    return k_pages, v_pages, None, None


def gather_paged_layer(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """One layer's pages -> contiguous [B, S_max, Kv, H] view (XLA Gather)."""
    Pp, Kv, page, H = pages.shape
    B, max_pages = page_table.shape
    out = pages[page_table]                 # [B, max_pages, Kv, page, H]
    out = out.transpose(0, 1, 3, 2, 4)      # [B, max_pages, page, Kv, H]
    return out.reshape(B, max_pages * page, Kv, H)


def gather_paged_layer_q(pages: jax.Array, scale_pages: jax.Array,
                         page_table: jax.Array):
    """Quantized gather: codes [B, Kv, S, H] + scales [B, Kv, S] — the
    kv-major order models.common.attend expects for int8 caches."""
    Pp, Kv, page, H = pages.shape
    B, max_pages = page_table.shape
    codes = pages[page_table]               # [B, mp, Kv, page, H]
    codes = codes.transpose(0, 2, 1, 3, 4).reshape(B, Kv, max_pages * page, H)
    sc = scale_pages[page_table]            # [B, mp, Kv*page]
    sc = sc.reshape(B, max_pages, Kv, page).transpose(0, 2, 1, 3)
    return codes, sc.reshape(B, Kv, max_pages * page)


# ---------------------------------------------------------------------------
# Write-combined decode window (serving hot path)
#
# Window-off, every step of a fused decode/spec block scatters its fresh
# K/V into the FULL [L, P, Kv, page, H] page pool via write_paged_layer —
# and because the pool rides the block scan's carry, XLA cannot alias the
# scatter in place: each step pays a pool-sized copy per pool tensor (the
# same term models/common.py's fused-generate window retired for the
# contiguous cache; BENCH_r05's 8x serving-vs-engine gap names it for the
# serving path). With kv_write_combine the pool is READ-ONLY inside the
# block: fresh K/V stages into a small per-slot window [L, S, Kv, W, H]
# riding the scan carry, attention reads pool + window, and the window
# flushes into the pool with ONE scatter per pool tensor per drain.
#
# The window stores the pool's EXACT representation (int8 codes + f32
# scales when the pool is quantized, pool dtype otherwise), and the
# non-kernel read path INSERTS the window entries into the gathered pool
# view at their absolute positions rather than concatenating a segment:
# the attend() call then runs on an element-wise identical operand set to
# the window-off write-then-gather path, so greedy serving outputs are
# byte-identical in both modes BY CONSTRUCTION (the parity contract
# tests/test_sched.py pins). Spec rollback is exact the same way: a
# rejected draft's K/V sits past win_len, is never attendable (insert
# positions >= any valid query) and is never flushed — the flushed pool
# never holds stale speculative state.
# ---------------------------------------------------------------------------


class KVWindow(NamedTuple):
    """Staged-but-unflushed K/V for every slot, all layers.

    k/v: [L, S, Kv, W, H] in the pool's representation (int8 codes when
    the pool is quantized, else the pool dtype); k/v_scale [L, S, Kv, W]
    f32 iff quantized. Entry w of slot s sits at absolute position
    lengths[s] + w of that slot's sequence, where lengths is the
    FLUSHED pool length; a separate win_len [S] vector (ridden through
    the block-scan carry beside this buffer, not stored here — it is
    shared by all layers) counts the valid entries per slot. Contents
    past win_len are stale garbage: masking, never zeroing, is the
    correctness mechanism (the buffer is recycled across blocks without
    a clear, like every other pool in this codebase)."""

    k: jax.Array
    v: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def width(self) -> int:
        return self.k.shape[3]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_kv_window(cache: PagedKVCache, width: int) -> KVWindow:
    """Allocate a window sized to `width` staged tokens per slot, in the
    pool's representation."""
    L, _, Kv, _, H = cache.k_pages.shape
    S = cache.num_slots
    shape = (L, S, Kv, width, H)
    if cache.quantized:
        return KVWindow(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32))
    return KVWindow(k=jnp.zeros(shape, cache.k_pages.dtype),
                    v=jnp.zeros(shape, cache.v_pages.dtype))


def stage_window_layer(wk, wv, k, v, win_len, wks=None, wvs=None):
    """Stage one layer's fresh K/V into its window slice.

    wk/wv: [S, Kv, W, H] (this layer's window); k/v: [B, T, Kv, H]
    floats (B == S); win_len: [S] valid entries BEFORE this call —
    token t of slot b lands at window index win_len[b] + t, quantized
    on the way in when scale slices wks/wvs [S, Kv, W] are given (the
    pool representation, so a later flush copies bytes verbatim and
    in-window attention dequantizes exactly like the pool read would).
    Indices never collide with valid entries (writes start AT win_len),
    so dead slots need no masking: their win_len never advances and
    their staged bytes stay unattendable garbage. Returns the updated
    (wk, wv, wks, wvs).
    """
    B, T = k.shape[0], k.shape[1]
    rows = jnp.arange(B)[:, None]                       # [B, 1]
    idx = win_len[:, None] + jnp.arange(T)[None, :]     # [B, T]
    if wks is not None:
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        wk = wk.at[rows, :, idx].set(kq, mode="drop")
        wv = wv.at[rows, :, idx].set(vq, mode="drop")
        wks = wks.at[rows, :, idx].set(ks, mode="drop")
        wvs = wvs.at[rows, :, idx].set(vs, mode="drop")
        return wk, wv, wks, wvs
    wk = wk.at[rows, :, idx].set(k.astype(wk.dtype), mode="drop")
    wv = wv.at[rows, :, idx].set(v.astype(wv.dtype), mode="drop")
    return wk, wv, None, None


def insert_window_view(view, wl, base):
    """Insert a layer's window entries into the gathered float view at
    their absolute positions: view [B, S_max, Kv, H], wl [S, Kv, W, H],
    base [S] flushed length per slot. Entries past a slot's valid count
    land at positions no causal query reaches (>= the query's own
    position) and positions past S_max drop, so the whole window inserts
    unconditionally — the result is element-wise identical to the
    window-off path's written pool view, which is the byte-parity
    contract."""
    B = view.shape[0]
    W = wl.shape[2]
    pos = base[:, None] + jnp.arange(W)[None, :]        # [B, W]
    return view.at[jnp.arange(B)[:, None], pos].set(
        wl.transpose(0, 2, 1, 3), mode="drop")


def insert_window_view_q(codes, scales, wl, wsl, base):
    """Quantized twin: codes [B, Kv, S_max, H] + scales [B, Kv, S_max]
    gain the window's codes wl [S, Kv, W, H] + scales wsl [S, Kv, W] at
    absolute positions."""
    B = codes.shape[0]
    W = wl.shape[2]
    rows = jnp.arange(B)[:, None]
    pos = base[:, None] + jnp.arange(W)[None, :]
    codes = codes.at[rows, :, pos].set(wl.transpose(0, 2, 1, 3),
                                       mode="drop")
    scales = scales.at[rows, :, pos].set(wsl.transpose(0, 2, 1),
                                         mode="drop")
    return codes, scales


def flush_paged_window(cache: PagedKVCache, window: KVWindow, win_len):
    """Flush every slot's staged window entries into the page pool: ONE
    scatter per pool tensor covering ALL layers (the window's write
    combining — the per-token path pays this scatter, and the carried
    pool copy behind it, once per token per layer).

    Entries past win_len (dead-step repeats, rejected speculative
    drafts) route to the null page exactly like write_paged_layer's
    inactive-slot writes — the flushed pool never holds them, which is
    what makes spec rollback exact for flushed state. Under mixed
    dispatch (ISSUE 18) prefill-chunk K/V stages through this same
    window: win_len for a prefill-phase slot grows by chunk widths
    rather than 1 per step, and an admission seeds the slot's win_len
    to 0 (the freed slot was flushed at its drain), so a fused block's
    staged prompt entries can never interleave with a predecessor's.
    Returns (cache with lengths advanced by win_len, zeroed win_len,
    flushed token count [scalar]).
    """
    L, Pp, Kv, page, H = cache.k_pages.shape
    S = win_len.shape[0]
    W = window.width
    mp = cache.page_table.shape[1]
    pos = cache.lengths[:, None] + jnp.arange(W)[None, :]     # [S, W]
    valid = jnp.arange(W)[None, :] < win_len[:, None]
    page_idx = jnp.take_along_axis(cache.page_table,
                                   jnp.clip(pos // page, 0, mp - 1), axis=1)
    page_idx = jnp.where(valid & (pos < mp * page), page_idx, Pp - 1)
    flat_pages = page_idx.reshape(-1)                          # [S*W]
    flat_off = (pos % page).reshape(-1)
    # advanced indices at dims 1 and 3 (slices between) put the index
    # dim FIRST: values arrive [S*W, L, Kv, H]
    kv_vals = window.k.transpose(1, 3, 0, 2, 4).reshape(S * W, L, Kv, H)
    vv_vals = window.v.transpose(1, 3, 0, 2, 4).reshape(S * W, L, Kv, H)
    k_pages = cache.k_pages.at[:, flat_pages, :, flat_off].set(kv_vals)
    v_pages = cache.v_pages.at[:, flat_pages, :, flat_off].set(vv_vals)
    ksp, vsp = cache.k_scale_pages, cache.v_scale_pages
    if window.quantized:
        # flat scale dim is kv-major: col = kv*page + offset; adjacent
        # advanced dims (1, 2) stay in place: values arrive [L, S*W, Kv]
        cols = jnp.arange(Kv)[None, :] * page + flat_off[:, None]
        ks_vals = window.k_scale.transpose(0, 1, 3, 2).reshape(L, S * W, Kv)
        vs_vals = window.v_scale.transpose(0, 1, 3, 2).reshape(L, S * W, Kv)
        ksp = ksp.at[:, flat_pages[:, None], cols].set(ks_vals)
        vsp = vsp.at[:, flat_pages[:, None], cols].set(vs_vals)
    cache = cache._replace(k_pages=k_pages, v_pages=v_pages,
                           k_scale_pages=ksp, v_scale_pages=vsp,
                           lengths=cache.lengths + win_len)
    return cache, jnp.zeros_like(win_len), win_len.sum()


def permute_window_tail(window: KVWindow, win_len, perm) -> KVWindow:
    """Compact a tree round's accepted path inside the window: the
    round staged its N chunk entries at window indices win_len ..
    win_len+N-1 (chunk-index order); `perm` [S, C] gives, for each of
    the C kept positions, the CHUNK index whose K/V belongs there
    (sampling.speculative_tree_accept's perm — the root->leaf accepted
    path). After this, window index win_len+i holds the i-th kept
    node's K/V, so the caller's win_len += m advance makes exactly the
    accepted path attendable/flushable and the rejected branches die
    past win_len, rollback-exact as ever.

    Pure gather (take_along_axis along the W axis with an identity
    index outside the staged run): the source materializes before the
    write, so overlapping src/dst positions are safe, and entries past
    the kept count are just the permuted leftovers — past win_len+m,
    unattendable, overwritten by the next round's staging."""
    W = window.width
    C = perm.shape[1]
    ar = jnp.arange(W)[None, :]                         # [1, W]
    rel = ar - win_len[:, None]                         # [S, W]
    tail = jnp.take_along_axis(perm, jnp.clip(rel, 0, C - 1), axis=1)
    idx = jnp.where((rel >= 0) & (rel < C),
                    win_len[:, None] + tail, ar)        # [S, W]
    gather = lambda a: jnp.take_along_axis(             # noqa: E731
        a, idx[None, :, None, :, None], axis=3)         # [L,S,Kv,W,H]
    k, v = gather(window.k), gather(window.v)
    ks = vs = None
    if window.quantized:
        gs = lambda a: jnp.take_along_axis(             # noqa: E731
            a, idx[None, :, None, :], axis=3)           # [L,S,Kv,W]
        ks, vs = gs(window.k_scale), gs(window.v_scale)
    return KVWindow(k=k, v=v, k_scale=ks, v_scale=vs)


def permute_paged_tail(cache: PagedKVCache, perm, active=None
                       ) -> PagedKVCache:
    """Window-off twin of permute_window_tail: the tree round's N chunk
    entries were written straight into the page pool at absolute
    positions lengths .. lengths+N-1 (write_paged_layer's start + chunk
    index); gather the C kept nodes' entries (positions lengths +
    perm[:, i]) and scatter them to the contiguous accepted positions
    lengths .. lengths+C-1 across all layers. Entries past the kept
    count m land past the advanced length — unattendable, overwritten
    by the next round's chunk at its new base. Inactive slots scatter
    to the null page (write_paged_layer's redirect)."""
    L, Pp, Kv, page, H = cache.k_pages.shape
    S, C = perm.shape
    mp = cache.page_table.shape[1]
    base = cache.lengths[:, None]                        # [S, 1]

    def flat(pos):
        pi = jnp.take_along_axis(cache.page_table,
                                 jnp.clip(pos // page, 0, mp - 1), axis=1)
        pi = jnp.where(pos < mp * page, pi, Pp - 1)
        if active is not None:
            pi = jnp.where(active[:, None], pi, Pp - 1)
        return pi.reshape(-1), (pos % page).reshape(-1)

    src_pages, src_off = flat(base + perm)
    dst_pages, dst_off = flat(base + jnp.arange(C)[None, :])
    # advanced indices at dims 1 and 3 (slice between) put the index
    # dim FIRST: values move as [S*C, L, Kv, H] (flush_paged_window's
    # idiom); the gather materializes before the scatter, so the
    # overlapping in-place permute is safe
    k_pages = cache.k_pages.at[:, dst_pages, :, dst_off].set(
        cache.k_pages[:, src_pages, :, src_off])
    v_pages = cache.v_pages.at[:, dst_pages, :, dst_off].set(
        cache.v_pages[:, src_pages, :, src_off])
    ksp, vsp = cache.k_scale_pages, cache.v_scale_pages
    if cache.quantized:
        # flat scale dim is kv-major: col = kv*page + offset; adjacent
        # advanced dims (1, 2) stay in place: values move [L, S*C, Kv]
        src_cols = jnp.arange(Kv)[None, :] * page + src_off[:, None]
        dst_cols = jnp.arange(Kv)[None, :] * page + dst_off[:, None]
        ksp = ksp.at[:, dst_pages[:, None], dst_cols].set(
            ksp[:, src_pages[:, None], src_cols])
        vsp = vsp.at[:, dst_pages[:, None], dst_cols].set(
            vsp[:, src_pages[:, None], src_cols])
    return cache._replace(k_pages=k_pages, v_pages=v_pages,
                          k_scale_pages=ksp, v_scale_pages=vsp)


# ---------------------------------------------------------------------------
# Paged forward pass (reference path; Pallas decode kernel lives in ops/)
# ---------------------------------------------------------------------------

def paged_layer_body(x, lp, kp, vp, *, cfg: ModelConfig, page_table,
                     positions, mask, cos, sin, active, use_kernel: bool,
                     fresh: bool, ksp=None, vsp=None, win=None,
                     force_dense: bool = False):
    """One transformer layer against one layer's page pool slice.

    Shared by paged_forward's full-stack scan, the stage-local scan of
    the pipeline serving path (parallel/pipeline.py), and the
    write-combined window path (paged_forward_window) so the three
    cannot drift. x: [B,T,D]; kp/vp: [P,Kv,page,H]; ksp/vsp: [P,Kv*page]
    scale slices iff the pool is int8. Returns (x, kp, vp[, ksp, vsp]).

    win (kv_write_combine): (wk, wv, wks, wvs, win_len) — this layer's
    window slices [S, Kv, W, H] (+ [S, Kv, W] scales iff quantized) and
    the per-slot staged count. The pool slice is then READ-ONLY: fresh
    K/V stages into the window instead of scattering the pool, and
    attention reads pool + window (kernel: window segment folded into
    the online softmax; dense: window inserted into the gathered view
    at absolute positions, element-wise identical to the window-off
    written view). Returns (x, wk, wv[, wks, wvs]) — the pool rides
    outside the scan unchanged.
    """
    T = x.shape[1]
    quant = ksp is not None
    compute_dtype = jnp.dtype(cfg.dtype)
    lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
    start = positions[:, 0]

    h = pre_norm(x, lp["ln1"], cfg)
    q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
    if win is not None:
        wk, wv, wks, wvs, win_len = win
        base = start - win_len  # flushed pool length per slot
        wk, wv, wks, wvs = stage_window_layer(wk, wv, k, v, win_len,
                                              wks, wvs)
    else:
        kp, vp, ksp, vsp = write_paged_layer(kp, vp, page_table, k, v,
                                             start, active, ksp, vsp)
    out = None
    if use_kernel and T == 1:
        if win is not None:
            # pool-valid lengths are the FLUSHED base; the staged run
            # (prior entries + the token just staged) rides as a window
            # segment with its own count
            lens = jnp.where(active, base, 0)
            wcnt = jnp.where(active, win_len + T, 0)
            out = paged_attention_sharded(q[:, 0], kp, vp, page_table,
                                          lens, ksp, vsp,
                                          win_k=wk, win_v=wv,
                                          win_count=wcnt,
                                          win_k_scale=wks, win_v_scale=wvs)
        else:
            # lengths INCLUDING the token just written (inactive: 0 ->
            # no pages visited, output discarded)
            lens = jnp.where(active, positions[:, 0] + 1, 0)
            out = paged_attention_sharded(q[:, 0], kp, vp, page_table,
                                          lens, ksp, vsp)
        out = out[:, None] if out is not None else None
    elif cfg.attn_impl == "flash" and T > 1 and fresh:
        # fresh prefill attends over the just-projected bf16 K/V, so the
        # kernel path is identical for int8 pools
        out = flash_attention_sharded(q, k, v, causal=True)
    elif cfg.attn_impl == "flash" and T > 1 and win is None \
            and not force_dense:
        # warm chunked prefill (ISSUE 13): the kernel attends the
        # CACHED prefix — the gathered pool view, count-masked per row
        # at the chunk's start (so the chunk's own just-written copy,
        # null-page garbage, and padding rows never contribute) — plus
        # the fresh chunk as causal blocks, one online-softmax state.
        # This replaces the dense O(T*S_max) materialized-scores
        # fallback every warm/chunked/prefix-hit prefill used to pay.
        # (The windowed verify path keeps the dense insert: staged
        # window entries are not in the pool.)
        base = jnp.where(active, start, 0)
        if quant:
            ckg, k_sg = gather_paged_layer_q(kp, ksp, page_table)
            cvg, v_sg = gather_paged_layer_q(vp, vsp, page_table)
            # mirror the chunk's in-pool representation (the dense path
            # reads the quantized write back) — operand-parity with the
            # gather path by construction
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            kf = (kq.astype(jnp.float32) * ksc[..., None]).astype(k.dtype)
            vf = (vq.astype(jnp.float32) * vsc[..., None]).astype(v.dtype)
            out = flash_attention_sharded(
                q, kf, vf, causal=True, prefix_k=ckg, prefix_v=cvg,
                prefix_len=base, prefix_k_scale=k_sg, prefix_v_scale=v_sg)
        else:
            ckg = gather_paged_layer(kp, page_table)
            cvg = gather_paged_layer(vp, page_table)
            out = flash_attention_sharded(q, k, v, causal=True,
                                          prefix_k=ckg, prefix_v=cvg,
                                          prefix_len=base)
    if out is None:
        # no mesh axis can shard the kernel operands (or kernels off):
        # dense gather attention, which GSPMD partitions itself.
        if quant:
            ck, k_s = gather_paged_layer_q(kp, ksp, page_table)
            cv, v_s = gather_paged_layer_q(vp, vsp, page_table)
            if win is not None:
                ck, k_s = insert_window_view_q(ck, k_s, wk, wks, base)
                cv, v_s = insert_window_view_q(cv, v_s, wv, wvs, base)
            out = attend(q, ck, cv, mask, cfg, k_s, v_s)
        else:
            ck = gather_paged_layer(kp, page_table)
            cv = gather_paged_layer(vp, page_table)
            if win is not None:
                ck = insert_window_view(ck, wk, base)
                cv = insert_window_view(cv, wv, base)
            out = attend(q, ck, cv, mask, cfg)
    x = x + attn_output(out, lp["attn"], cfg)
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    if win is not None:
        if quant:
            return x, wk, wv, wks, wvs
        return x, wk, wv
    if quant:
        return x, kp, vp, ksp, vsp
    return x, kp, vp


def paged_forward(params, cfg: ModelConfig, tokens: jax.Array,
                  cache: PagedKVCache,
                  positions: Optional[jax.Array] = None,
                  active: Optional[jax.Array] = None,
                  use_kernel: bool = False,
                  fresh: bool = False,
                  last_index: Optional[jax.Array] = None,
                  attn_mask: Optional[jax.Array] = None):
    """Forward over [B,T] tokens against the paged cache.

    B must equal cache.num_slots (serving: one row per slot). `active`
    [B] bool masks slots with no live request: their lengths don't
    advance and their writes land on pages only they own (admission wrote
    their table), so garbage never leaks across requests. Returns
    (logits [B,T,V], updated cache).

    use_kernel: decode steps (T==1) attend through the Pallas paged-
    attention kernel — touches only each slot's live pages instead of
    gathering the full S_max view. Prefills (T>1) honor cfg.attn_impl
    ("flash" = Pallas blockwise kernel over the fresh K/V).

    last_index [B]: run the LM head only on each row's hidden state at
    that index — logits come back [B,1,V] (models.common.forward docs:
    the full-T head dominates prefill memory at LLM vocab sizes).

    attn_mask [B,T,S_max]: replace the causal make_mask with an
    explicit attention mask (the tree-verify path: each node attends
    committed history + its ancestor chunk positions only). Forces the
    dense gather path — the chunk is NOT causal, so neither flash
    branch may see it. K/V writes still land at start + chunk index
    (write_paged_layer's arange), while RoPE follows `positions`
    (base + tree depth): after the accepted-path compaction the kept
    entries' storage positions equal their RoPE positions again.
    """
    B, T = tokens.shape
    quant = cache.quantized
    if positions is None:
        positions = cache.lengths[:, None] + jnp.arange(T)[None, :]
    if active is None:
        active = jnp.ones((B,), bool)

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = attn_mask if attn_mask is not None \
        else make_mask(positions, cache.max_seq)
    mask = mask & active[:, None, None]

    def body(x, scanned):
        lp, kp, vp, *scales = scanned
        out = paged_layer_body(
            x, lp, kp, vp, cfg=cfg, page_table=cache.page_table,
            positions=positions, mask=mask, cos=cos, sin=sin, active=active,
            use_kernel=use_kernel, fresh=fresh,
            ksp=scales[0] if scales else None,
            vsp=scales[1] if scales else None,
            force_dense=attn_mask is not None)
        return out[0], tuple(out[1:])

    xs = (params["layers"], cache.k_pages, cache.v_pages)
    if quant:
        xs = xs + (cache.k_scale_pages, cache.v_scale_pages)
    x, new_pools = lax.scan(body, x, xs)
    if last_index is not None:
        x = jnp.take_along_axis(
            x, last_index[:, None, None].astype(jnp.int32), axis=1)
    logits = final_logits(params, cfg, x)
    new_len = jnp.where(active, cache.lengths + T, cache.lengths)
    return logits, PagedKVCache(new_pools[0], new_pools[1],
                                cache.page_table, new_len, *new_pools[2:])


def paged_forward_window(params, cfg: ModelConfig, tokens: jax.Array,
                         cache: PagedKVCache, window: KVWindow, win_len,
                         active: Optional[jax.Array] = None,
                         use_kernel: bool = False,
                         positions: Optional[jax.Array] = None,
                         attn_mask: Optional[jax.Array] = None):
    """Windowed (kv_write_combine) forward over [B,T] tokens: the pool
    is READ-ONLY, fresh K/V stages into `window` at per-slot offset
    win_len, and attention reads pool + window.

    The per-slot true length is cache.lengths (FLUSHED tokens) +
    win_len (staged), which replaces window-off paged_forward's
    positions derivation; neither cache.lengths nor win_len advances
    here — the block scan advances win_len by what it actually keeps
    (1 per live decode step; the accepted count m per spec round, which
    is what makes rollback exact: rejected entries stay past win_len,
    unattendable and never flushed). Returns (logits [B,T,V], updated
    window).

    The pool is closed over and indexed in-body (lax.dynamic_index) à
    la models/common._decode_forward — threading the read-only pools
    through scan xs would materialize a layer-slice copy per step. Only
    the small window leaves ride the scan as xs/ys.

    `positions`/`attn_mask` override the causal defaults for the
    tree-verify path (paged_forward's attn_mask docs): staging still
    lands token t at window index win_len + t, positions carry
    base + tree depth for RoPE, and the explicit mask forces the dense
    insert path.
    """
    B, T = tokens.shape
    quant = cache.quantized
    if active is None:
        active = jnp.ones((B,), bool)
    if positions is None:
        positions = (cache.lengths + win_len)[:, None] \
            + jnp.arange(T)[None, :]
    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = attn_mask if attn_mask is not None \
        else make_mask(positions, cache.max_seq)
    mask = mask & active[:, None, None]

    def body(carry, scanned):
        x, i = carry
        lp, wk, wv, *wsc = scanned
        kp = lax.dynamic_index_in_dim(cache.k_pages, i, 0, keepdims=False)
        vp = lax.dynamic_index_in_dim(cache.v_pages, i, 0, keepdims=False)
        ksp = vsp = None
        if quant:
            ksp = lax.dynamic_index_in_dim(cache.k_scale_pages, i, 0,
                                           keepdims=False)
            vsp = lax.dynamic_index_in_dim(cache.v_scale_pages, i, 0,
                                           keepdims=False)
        wks, wvs = wsc if wsc else (None, None)
        out = paged_layer_body(
            x, lp, kp, vp, cfg=cfg, page_table=cache.page_table,
            positions=positions, mask=mask, cos=cos, sin=sin,
            active=active, use_kernel=use_kernel, fresh=False,
            ksp=ksp, vsp=vsp, win=(wk, wv, wks, wvs, win_len))
        return (out[0], i + 1), tuple(out[1:])

    xs = (params["layers"], window.k, window.v)
    if quant:
        xs = xs + (window.k_scale, window.v_scale)
    (x, _), new_win = lax.scan(body, (x, 0), xs)
    logits = final_logits(params, cfg, x)
    return logits, KVWindow(*new_win)
