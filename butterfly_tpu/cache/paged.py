"""Paged KV cache: block-table layout for continuous batching.

Realizes BASELINE.json configs[4] ("continuous batching + paged KV cache");
the reference has no implementation (SURVEY.md §0). Design (vLLM-style
semantics, TPU-native mechanics):

* One global page pool per layer stack: k/v_pages [L, P, page, Kv, H] in
  HBM. Sequences own pages through a block table [slots, max_pages] of
  page ids; page P-1 is reserved as the null page (block tables are
  initialized to it, so gathers from unallocated slots read zeros and the
  causal mask hides them).
* Token writes are scatters (`.at[...].set`) at (page_table[slot, t//page],
  t%page) — XLA Scatter keeps the pool HBM-resident, the paged analogue of
  the contiguous cache's DynamicUpdateSlice.
* Attention reads gather each slot's pages back into a contiguous
  [B, S_max, Kv, H] view per layer (XLA Gather). This reference path reads
  the same bytes a contiguous cache would; the Pallas paged-attention
  kernel (ops/) replaces gather+attend for decode so only *used* pages are
  touched.
* Page allocation/free is host-side (cache/allocator.py) — the device
  never sees dynamic shapes, only a static pool and int32 tables.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from butterfly_tpu.core.config import ModelConfig, RuntimeConfig


class PagedKVCache(NamedTuple):
    k_pages: jax.Array     # [L, P, page, Kv, H]
    v_pages: jax.Array     # [L, P, page, Kv, H]
    page_table: jax.Array  # [slots, max_pages] int32, null = P-1
    lengths: jax.Array     # [slots] int32 tokens written per slot

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def null_page(self) -> int:
        return self.k_pages.shape[1] - 1

    @property
    def max_seq(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def num_slots(self) -> int:
        return self.page_table.shape[0]


def init_paged_cache(cfg: ModelConfig, runtime: RuntimeConfig,
                     dtype: Optional[jnp.dtype] = None) -> PagedKVCache:
    """Pool sized from the runtime config (+1 reserved null page)."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    page = runtime.page_size
    max_pages = -(-runtime.max_seq_len // page)
    P = runtime.num_pages or runtime.max_batch_size * max_pages
    P += 1  # null page
    shape = (cfg.num_layers, P, page, cfg.num_kv_heads, cfg.head_dim)
    return PagedKVCache(
        k_pages=jnp.zeros(shape, dtype),
        v_pages=jnp.zeros(shape, dtype),
        page_table=jnp.full((runtime.max_batch_size, max_pages), P - 1,
                            jnp.int32),
        lengths=jnp.zeros((runtime.max_batch_size,), jnp.int32),
    )


def write_paged_layer(k_pages: jax.Array, v_pages: jax.Array,
                      page_table: jax.Array, k: jax.Array, v: jax.Array,
                      start: jax.Array,
                      active: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Scatter new tokens into one layer's page pool.

    k_pages/v_pages: [P, page, Kv, H]; k/v: [B, T, Kv, H] (T new tokens per
    slot); start: [B] first absolute position of each slot's new tokens.
    Inactive slots' writes are redirected to the null page. Positions past
    a slot's allocated pages must not occur for active slots (the host
    allocator guarantees capacity before scheduling the step).
    """
    Pp, page, Kv, H = k_pages.shape
    B, T = k.shape[0], k.shape[1]
    pos = start[:, None] + jnp.arange(T)[None, :]          # [B,T] absolute
    page_idx = jnp.take_along_axis(page_table, pos // page, axis=1)  # [B,T]
    # Prefill buckets pad T past the true prompt, so pos can exceed the
    # table row's capacity. Route those positions to the null page
    # explicitly rather than relying on take_along_axis's out-of-bounds
    # fill (INT32_MIN) being dropped by the scatter below.
    page_idx = jnp.where(pos < page_table.shape[1] * page, page_idx, Pp - 1)
    if active is not None:
        page_idx = jnp.where(active[:, None], page_idx, Pp - 1)
    offset = pos % page                                     # [B,T]
    flat_pages = page_idx.reshape(-1)
    flat_off = offset.reshape(-1)
    kf = k.reshape(B * T, Kv, H).astype(k_pages.dtype)
    vf = v.reshape(B * T, Kv, H).astype(v_pages.dtype)
    k_pages = k_pages.at[flat_pages, flat_off].set(kf)
    v_pages = v_pages.at[flat_pages, flat_off].set(vf)
    return k_pages, v_pages


def gather_paged_layer(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """One layer's pages -> contiguous [B, S_max, Kv, H] view (XLA Gather)."""
    Pp, page, Kv, H = pages.shape
    B, max_pages = page_table.shape
    out = pages[page_table]                 # [B, max_pages, page, Kv, H]
    return out.reshape(B, max_pages * page, Kv, H)


# ---------------------------------------------------------------------------
# Paged forward pass (reference path; Pallas decode kernel lives in ops/)
# ---------------------------------------------------------------------------

def paged_layer_body(x, lp, kp, vp, *, cfg: ModelConfig, page_table,
                     positions, mask, cos, sin, active, use_kernel: bool,
                     fresh: bool):
    """One transformer layer against one layer's page pool slice.

    Shared by paged_forward's full-stack scan and the stage-local scan of
    the pipeline serving path (parallel/pipeline.py) so the two cannot
    drift. x: [B,T,D]; kp/vp: [P,page,Kv,H]; returns (x, kp, vp).
    """
    from butterfly_tpu.models.common import (
        _cast_float, attend, attn_output, ffn_block, pre_norm, qkv_proj)

    T = x.shape[1]
    compute_dtype = jnp.dtype(cfg.dtype)
    lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
    start = positions[:, 0]

    h = pre_norm(x, lp["ln1"], cfg)
    q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
    kp, vp = write_paged_layer(kp, vp, page_table, k, v, start, active)
    out = None
    if use_kernel and T == 1:
        from butterfly_tpu.ops.paged_attention import paged_attention_sharded
        # lengths INCLUDING the token just written (inactive: 0 -> no
        # pages visited, output discarded)
        lens = jnp.where(active, positions[:, 0] + 1, 0)
        out = paged_attention_sharded(q[:, 0], kp, vp, page_table, lens)
        out = out[:, None] if out is not None else None
    elif cfg.attn_impl == "flash" and T > 1 and fresh:
        from butterfly_tpu.ops.flash_attention import flash_attention_sharded
        out = flash_attention_sharded(q, k, v, causal=True)
    if out is None:
        # no mesh axis can shard the kernel operands (or kernels off):
        # dense gather attention, which GSPMD partitions itself.
        ck = gather_paged_layer(kp, page_table)
        cv = gather_paged_layer(vp, page_table)
        out = attend(q, ck, cv, mask, cfg)
    x = x + attn_output(out, lp["attn"], cfg)
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    return x, kp, vp


def paged_forward(params, cfg: ModelConfig, tokens: jax.Array,
                  cache: PagedKVCache,
                  positions: Optional[jax.Array] = None,
                  active: Optional[jax.Array] = None,
                  use_kernel: bool = False,
                  fresh: bool = False):
    """Forward over [B,T] tokens against the paged cache.

    B must equal cache.num_slots (serving: one row per slot). `active`
    [B] bool masks slots with no live request: their lengths don't
    advance and their writes land on pages only they own (admission wrote
    their table), so garbage never leaks across requests. Returns
    (logits [B,T,V], updated cache).

    use_kernel: decode steps (T==1) attend through the Pallas paged-
    attention kernel — touches only each slot's live pages instead of
    gathering the full S_max view. Prefills (T>1) honor cfg.attn_impl
    ("flash" = Pallas blockwise kernel over the fresh K/V).
    """
    from butterfly_tpu.models.common import embed_tokens, final_logits, make_mask

    B, T = tokens.shape
    if positions is None:
        positions = cache.lengths[:, None] + jnp.arange(T)[None, :]
    if active is None:
        active = jnp.ones((B,), bool)

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)
    mask = mask & active[:, None, None]

    def body(x, scanned):
        lp, kp, vp = scanned
        x, kp, vp = paged_layer_body(
            x, lp, kp, vp, cfg=cfg, page_table=cache.page_table,
            positions=positions, mask=mask, cos=cos, sin=sin, active=active,
            use_kernel=use_kernel, fresh=fresh)
        return x, (kp, vp)

    x, (new_k, new_v) = lax.scan(
        body, x, (params["layers"], cache.k_pages, cache.v_pages))
    logits = final_logits(params, cfg, x)
    new_len = jnp.where(active, cache.lengths + T, cache.lengths)
    return logits, PagedKVCache(new_k, new_v, cache.page_table, new_len)
