"""Host-side page allocator for the paged KV cache.

Free-list bookkeeping (the device only ever sees the static page pool
and int32 block tables — no dynamic shapes under jit). The scheduler
asks `can_grow`/`grow` before every device step; a refusal means the
request must wait or a running one must be preempted (sched/scheduler.py
policy). Page P-1 is the reserved null page (cache/paged.py) and is
never handed out.

Two interchangeable backends (identical semantics, parity-tested in
tests/test_native.py): this pure-Python class, and the C++ free list in
native/allocator.cc loaded via ctypes (butterfly_tpu.native). Use
`make_page_allocator` to get the native one when the lib is built.
"""
from __future__ import annotations

from typing import Dict, List, Optional


class PageAllocator:
    """Free-list allocator over `num_pages` usable pages per slot table."""

    def __init__(self, num_pages: int, page_size: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self._free: List[int] = list(range(num_pages - 1, -1, -1))
        self._owned: Dict[int, List[int]] = {}  # slot -> page ids, in order

    # -- queries ------------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_of(self, slot: int) -> List[int]:
        return list(self._owned.get(slot, ()))

    def pages_needed(self, slot: int, new_length: int) -> int:
        have = len(self._owned.get(slot, ()))
        want = -(-new_length // self.page_size)
        return max(0, want - have)

    def can_grow(self, slot: int, new_length: int) -> bool:
        if new_length > self.max_pages_per_seq * self.page_size:
            return False
        return self.pages_needed(slot, new_length) <= self.free_pages

    # -- mutations ----------------------------------------------------------

    def _take_free(self) -> int:
        """Pop one free page. Subclass hook: PrefixCachingAllocator
        evicts a warm cached page here when the raw free list is dry."""
        return self._free.pop()

    def grow(self, slot: int, new_length: int) -> Optional[List[int]]:
        """Allocate pages so `slot` can hold new_length tokens.

        Returns the newly allocated page ids (possibly empty), or None if
        out of pages / over the per-seq limit — in that case nothing is
        allocated (all-or-nothing).
        """
        if not self.can_grow(slot, new_length):
            return None
        n = self.pages_needed(slot, new_length)
        fresh = [self._take_free() for _ in range(n)]
        self._owned.setdefault(slot, []).extend(fresh)
        return fresh

    def release(self, slot: int) -> List[int]:
        """Free all pages of `slot` (request finished or preempted)."""
        pages = self._owned.pop(slot, [])
        self._free.extend(reversed(pages))
        return pages

    # -- prefix-caching interface (no-op here; cache/prefix.py overrides) ----

    def admit(self, slot: int, tokens, need_len: int) -> Optional[int]:
        """Allocate a fresh slot through need_len tokens; returns the
        number of prompt tokens already cached (always 0 here) or None
        if it cannot fit. PrefixCachingAllocator shares matched pages."""
        return None if self.grow(slot, need_len) is None else 0

    def register(self, slot: int, tokens) -> int:
        """Publish a slot's pages for reuse (no registry here)."""
        return 0


def make_page_allocator(num_pages: int, page_size: int,
                        max_pages_per_seq: int, num_slots: int = 4096):
    """Native (C++) allocator when the lib is built, else pure Python."""
    from butterfly_tpu.native import NativePageAllocator, native_available
    if native_available():
        return NativePageAllocator(num_pages, page_size, max_pages_per_seq,
                                   num_slots)
    return PageAllocator(num_pages, page_size, max_pages_per_seq)
