"""Tokenizers.

`ByteTokenizer` is the dependency-free default (UTF-8 bytes + specials) so
the framework runs end-to-end with zero downloaded assets. `load_tokenizer`
upgrades to a HF tokenizer when one is available locally (offline-safe:
never hits the network).
"""
from __future__ import annotations

from typing import List, Optional, Sequence


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: ids 0..255 are bytes, 256=BOS, 257=EOS."""

    vocab_size = 258
    bos_id = 256
    eos_id = 257

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] if add_bos else []) + ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Thin adapter over a transformers tokenizer loaded from local files."""

    def __init__(self, tok):
        self.tok = tok
        self.vocab_size = tok.vocab_size
        self.bos_id = tok.bos_token_id
        self.eos_id = tok.eos_token_id

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = self.tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        return self.tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path_or_name: Optional[str] = None):
    """Local HF tokenizer if `path_or_name` resolves offline; else bytes."""
    if path_or_name:
        try:
            from transformers import AutoTokenizer
            tok = AutoTokenizer.from_pretrained(path_or_name,
                                                local_files_only=True)
            return HFTokenizer(tok)
        except Exception:
            pass
    return ByteTokenizer()
