from butterfly_tpu.core.config import ModelConfig, MeshConfig, RuntimeConfig  # noqa: F401
from butterfly_tpu.core.mesh import make_mesh, local_mesh  # noqa: F401
