"""Configuration dataclasses for models, meshes, and the runtime.

The reference scaffold prescribes a config/flag system only by implication
(/root/reference/CLAUDE.md:25-27 — "To be added once build system is
established"); we use plain frozen dataclasses: hashable (usable as jit
static args), serializable, no global state.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters for a transformer LM.

    One config class covers the three model families (GPT-2, Llama-3,
    Mixtral) — the family is selected by `arch` and the MoE fields.
    """

    arch: str = "llama"  # "gpt2" | "llama" | "mixtral"
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32  # < num_heads => grouped-query attention
    head_dim: int = 128
    intermediate_size: int = 11008
    max_seq_len: int = 8192

    # normalization / activations
    norm_eps: float = 1e-5
    use_bias: bool = False            # gpt2: True
    tie_embeddings: bool = False      # gpt2: True
    act: str = "silu"                 # gpt2: "gelu_new"; llama/mixtral: "silu"

    # positional encoding
    pos_embedding: str = "rope"       # "rope" | "learned"
    rope_theta: float = 500000.0

    # MoE (mixtral)
    num_experts: int = 0              # 0 => dense FFN
    num_experts_per_tok: int = 2
    moe_impl: str = "dense"           # "dense" | "ep" (GShard dispatch)
    moe_capacity_factor: float = 2.0  # per-expert slots multiplier (ep)

    # numerics
    dtype: str = "bfloat16"           # activation/weight compute dtype
    param_dtype: str = "float32"      # master param dtype

    # attention implementation: "dense" = XLA einsum attend over the cache;
    # "flash" = Pallas blockwise kernel — fresh prefills attend the
    # freshly-projected K/V, warm multi-token steps (chunk continuations,
    # prefix-cache resumes) fold the cached context in as a count-masked
    # prefix segment (ops/flash_attention.py warm-prefix prefill); the
    # engines swap it in for exactly those steps.
    attn_impl: str = "dense"

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------------
# Presets (BASELINE.json configs[0..3] model families)
# ---------------------------------------------------------------------------

def gpt2_124m() -> ModelConfig:
    return ModelConfig(
        arch="gpt2", vocab_size=50257, hidden_size=768, num_layers=12,
        num_heads=12, num_kv_heads=12, head_dim=64, intermediate_size=3072,
        max_seq_len=1024, norm_eps=1e-5, use_bias=True, tie_embeddings=True,
        act="gelu_new", pos_embedding="learned",
    )


def llama3_8b() -> ModelConfig:
    return ModelConfig(
        arch="llama", vocab_size=128256, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=14336,
        max_seq_len=8192, rope_theta=500000.0,
    )


def llama3_70b() -> ModelConfig:
    return ModelConfig(
        arch="llama", vocab_size=128256, hidden_size=8192, num_layers=80,
        num_heads=64, num_kv_heads=8, head_dim=128, intermediate_size=28672,
        max_seq_len=8192, rope_theta=500000.0,
    )


def mixtral_8x7b() -> ModelConfig:
    return ModelConfig(
        arch="mixtral", vocab_size=32000, hidden_size=4096, num_layers=32,
        num_heads=32, num_kv_heads=8, head_dim=128, intermediate_size=14336,
        max_seq_len=32768, rope_theta=1000000.0,
        num_experts=8, num_experts_per_tok=2,
    )


def tiny(arch: str = "llama", **kw) -> ModelConfig:
    """Small config for tests: runs in <1s on CPU, exercises every code path."""
    base = dict(
        # 258 = ByteTokenizer vocab (bytes + BOS/EOS) so the CLI demo works.
        vocab_size=258, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, head_dim=16, intermediate_size=128, max_seq_len=128,
    )
    if arch == "gpt2":
        base.update(num_kv_heads=4, use_bias=True, tie_embeddings=True,
                    act="gelu_new", pos_embedding="learned")
    if arch == "mixtral":
        base.update(num_experts=4, num_experts_per_tok=2)
    base.update(kw)
    return ModelConfig(arch=arch, **base)


PRESETS = {
    "gpt2-124m": gpt2_124m,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "mixtral-8x7b": mixtral_8x7b,
}


# ---------------------------------------------------------------------------
# Mesh / parallelism config
# ---------------------------------------------------------------------------

#: Canonical mesh axis names, outermost-first. Collectives over `tensor`
#: (innermost) ride the fastest ICI links; `data` (outermost) may span DCN.
MESH_AXES: Tuple[str, ...] = ("data", "stage", "expert", "seq", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes of the parallelism axes; the product must equal device count.

    data   : data parallel (replicated params, sharded batch)
    stage  : pipeline parallel (layer groups, ppermute handoff)
    expert : MoE expert parallel (all_to_all token routing)
    seq    : sequence/context parallel (ring attention / Ulysses)
    tensor : tensor parallel (Megatron row/column sharding, psum)
    """

    data: int = 1
    stage: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    @property
    def axis_sizes(self) -> Tuple[int, ...]:
        return (self.data, self.stage, self.expert, self.seq, self.tensor)

    @property
    def num_devices(self) -> int:
        n = 1
        for s in self.axis_sizes:
            n *= s
        return n

    def replace(self, **kw) -> "MeshConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class RuntimeConfig:
    """Serving/engine runtime knobs (BASELINE.json configs[4] surface)."""

    max_batch_size: int = 8
    max_seq_len: int = 2048
    prefill_chunk: int = 512          # max prefill tokens per scheduler tick;
                                      # long prompts continue across ticks.
                                      # NB: chunks pad to the engine's
                                      # 16-token bucket floor — values < 16
                                      # add compute without cutting latency
    prefill_max_batch: int = 8        # max waiting requests gang-admitted
                                      # into ONE batched [B, Tbucket]
                                      # prefill dispatch per scheduler
                                      # tick (sched/scheduler.py group
                                      # admission). B is bucketed to the
                                      # next power of two (clamped here)
                                      # so at most log2(this)+1 batch
                                      # shapes ever compile per T bucket
    mixed_dispatch: bool = True       # fused mixed dispatch: each tick's
                                      # jitted block carries BOTH phases —
                                      # decode/spec slots advance tokens
                                      # while freshly admitted slots chew
                                      # budget-bounded prefill chunks in
                                      # the same scan (per-slot phase
                                      # masks + chunk cursors riding the
                                      # carry), retiring admission-cause
                                      # drain barriers as a class. False
                                      # = the alternating prefill/decode
                                      # path, the parity reference.
                                      # Continuous scheduler only; falls
                                      # back to alternating for stateful
                                      # (model) draft sources
    prefill_inline_budget: int = 32   # mixed dispatch: max prefill
                                      # tokens chewed per scan STEP
                                      # across all prefilling slots —
                                      # the ITL-tail knob. Each
                                      # prefilling slot consumes a
                                      # C-token chunk per step; this
                                      # bounds how many slots may be in
                                      # prefill phase concurrently
                                      # (budget // C), trading admission
                                      # throughput against decode-slot
                                      # step latency
    seq_parallel_threshold: int = 0   # long-prompt admission lane: a
                                      # waiting prompt LONGER than this
                                      # routes its prefill through
                                      # chunked seq-parallel dispatches
                                      # (ring attention over the mesh's
                                      # seq axis, engine.sp_prefill_chunk)
                                      # whose K/V lands in the ordinary
                                      # page pool — prefix-registry-
                                      # visible, evictable, exportable —
                                      # then decodes as a normal paged
                                      # slot. 0 = off (every prompt
                                      # takes the single-device chunk
                                      # path). Needs a mesh with seq > 1
                                      # and stage == 1; ignored (with a
                                      # warning) otherwise
    seq_parallel_chunk: int = 0       # tokens per seq-parallel prefill
                                      # dispatch (rounded up to a
                                      # multiple of the seq degree N).
                                      # 0 = auto: N * prefill_chunk —
                                      # each shard chews a prefill_chunk
                                      # worth of work per dispatch
    page_size: int = 16               # paged-KV tokens per block
    num_pages: int = 0                # 0 => derive from max_batch/max_seq
    scheduler: str = "continuous"     # "continuous" (chunked-prefill/decode
                                      # interleave) | "static" (drain batches)
    max_queue: int = 256
    decode_steps_per_tick: int = 1    # fused decode block width: the
                                      # scheduler runs this many decode
                                      # iterations per tick() inside ONE
                                      # jitted scan (one dispatch + one
                                      # stacked drain per tick)
    inflight_blocks: int = 2          # decode blocks kept IN FLIGHT on
                                      # the device: block t+1 chains on
                                      # block t's device-resident carry
                                      # before t is drained, so host
                                      # scheduling overlaps device
                                      # compute (dispatch-ahead). 1 =
                                      # the synchronous drain-every-tick
                                      # loop; membership changes force a
                                      # drain barrier regardless
    prefix_caching: bool = False      # content-hash KV page reuse across
                                      # requests (cache/prefix.py): shared
                                      # prompt prefixes skip prefill entirely
    prefill_flash_warm: bool = True   # warm-prefix flash prefill: the
                                      # serving engine's WARM prefill
                                      # program (chunk continuations,
                                      # prefix-cache resumes) compiles
                                      # with the flash kernel attending
                                      # cached prefix + fresh chunk,
                                      # instead of the dense O(T*S)
                                      # gather fallback; also lets a
                                      # prefill gang mix fresh and warm
                                      # members in one dispatch (the
                                      # all-or-nothing freshness
                                      # downgrade is gone). Only
                                      # engages where kernels do
                                      # (use_kernels, i.e. TPU by
                                      # default); False = dense warm
                                      # prefill, the parity reference
    kv_quant: str = "none"            # "int8" stores the contiguous KV
                                      # cache as int8 codes + per-vector
                                      # scales: half the HBM bytes in the
                                      # bandwidth-bound decode loop
    kv_write_combine: bool = True     # serving-path write-combined KV
                                      # decode window: fused decode/spec
                                      # blocks stage fresh K/V in a small
                                      # per-slot window riding the scan
                                      # carry (the page pool is READ-ONLY
                                      # inside the block) and the window
                                      # flushes with ONE pool scatter per
                                      # drain instead of one per token —
                                      # the serving twin of decode_window
                                      # below. Greedy outputs are
                                      # byte-identical either way (the
                                      # window stores the pool's exact
                                      # representation); False = the
                                      # per-token write_paged_layer path.
                                      # Ignored (per-token writes) under
                                      # pipeline (stage>1) serving
    host_kv_tier_mb: float = 0.0      # host-RAM KV tier capacity in MB
                                      # (cache/hosttier.py): > 0 turns
                                      # prefix-cache eviction into
                                      # evict-to-host — recycled pages
                                      # park their bytes in host DRAM
                                      # keyed by chain digest and revive
                                      # on the next prefix hit instead
                                      # of re-prefilling. Requires
                                      # prefix_caching; 0 = off (drop
                                      # on evict, the pre-tier behavior)
    host_kv_tier_dir: Optional[str] = None
                                      # optional disk-spill directory
                                      # for the host tier: pages LRU'd
                                      # out of the RAM budget demote to
                                      # one .npz each instead of being
                                      # dropped, and promote back on
                                      # access. None = RAM only
    decode_window: int = 0            # fused-generate write combining:
                                      # decode this many tokens into a
                                      # small window, flush to the cache
                                      # in one write. 1 = per-step
                                      # writes; 0 = auto (16 with an
                                      # int8 cache — measured best on
                                      # v5e — else 1)
    speculative_gamma: int = 0        # serving-path speculative
                                      # decoding: draft this many
                                      # tokens per slot per round and
                                      # verify ALL slots in one batched
                                      # (gamma+1)-token forward, with
                                      # accept/rollback computed on
                                      # device inside the fused spec
                                      # block (engine._spec_scan).
                                      # Sampling-safe: temperature /
                                      # top-k / top-p requests get the
                                      # exact rejection-sampling
                                      # correction. 0 = off
    speculative_ngram: int = 2        # lookup ngram for the drafts
    draft_model: str = "ngram"        # draft source for the spec block
                                      # (engine.serving.DRAFT_SOURCES):
                                      # "ngram" = model-free prompt
                                      # lookup over the device-side
                                      # token history (free, but earns
                                      # ~0 on non-repetitive traffic);
                                      # "model" = a real on-device
                                      # draft model (models/draft.py)
                                      # whose per-round γ-step forward
                                      # runs INSIDE the jitted spec
                                      # scan, over its own
                                      # rollback-exact KV cache riding
                                      # the block carry. Custom sources
                                      # plug in via
                                      # register_draft_source
    draft_layers: int = 0             # "model" source, derivation: use
                                      # the first draft_layers layers
                                      # of the TARGET checkpoint as the
                                      # draft (embed/final-norm/unembed
                                      # shared by reference — zero
                                      # extra HBM for them; resident on
                                      # the same chip). 0 = auto
                                      # (num_layers/4, floored at 1).
                                      # Ignored when draft_ckpt is set
    draft_ckpt: Optional[str] = None  # "model" source, loading: an
                                      # independent HF-format draft
                                      # checkpoint (narrow config, SAME
                                      # vocabulary — validated) loaded
                                      # through the existing ckpt
                                      # machinery instead of deriving
                                      # by truncation
    spec_tree_width: int = 0          # token-TREE speculation
                                      # (SpecInfer-style): branch this
                                      # many sibling candidates from the
                                      # draft's per-position q at every
                                      # expansion depth and verify the
                                      # whole tree in ONE forward per
                                      # round via a tree-attention mask
                                      # (engine._spec_tree_scan). The
                                      # recursive-residual rejection
                                      # walk keeps the output
                                      # distribution exactly the
                                      # target's. Requires a draft
                                      # source with tree_draft (the
                                      # "model" source). 0/1 = linear
                                      # γ-chain speculation (the
                                      # speculative_gamma path)
    spec_tree_nodes: int = 0          # total node budget N of the token
                                      # tree, INCLUDING the root chain
                                      # token ((N-1) must be divisible
                                      # by spec_tree_width — full
                                      # sibling fans only). 0 = auto:
                                      # γ+1 nodes, so tree-vs-linear
                                      # comparisons at the same gamma
                                      # hold verify FLOPs equal
    top_k: int = 0                    # serving-wide sampling filters
    top_p: float = 1.0
    port: int = 8000

    def replace(self, **kw) -> "RuntimeConfig":
        return dataclasses.replace(self, **kw)
