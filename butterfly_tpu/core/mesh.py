"""Device mesh bringup.

TPU-native replacement for the reference's planned "communication layer"
bootstrap (/root/reference/CLAUDE.md:20): instead of NCCL communicator
setup, we build a `jax.sharding.Mesh` whose axis order maps parallelism
kinds onto the ICI topology — `tensor` innermost (fastest links, all-reduce
every layer), `data` outermost (least traffic, may cross DCN).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import MESH_AXES, MeshConfig


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the canonical axes (data, stage, expert, seq, tensor).

    Axis sizes of 1 are kept (not squeezed) so PartitionSpecs can always
    name every axis; XLA elides collectives over size-1 axes for free.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices != n:
        raise ValueError(
            f"MeshConfig wants {cfg.num_devices} devices "
            f"({dict(zip(MESH_AXES, cfg.axis_sizes))}) but {n} are available"
        )
    dev_array = np.asarray(devices).reshape(cfg.axis_sizes)
    return Mesh(dev_array, MESH_AXES)


def local_mesh() -> Mesh:
    """Single-device mesh (all axes size 1) — the CPU/1-chip dev loop."""
    return make_mesh(MeshConfig(), devices=jax.devices()[:1])


def mesh_for(n_devices: int, tensor: int = 0, stage: int = 1, expert: int = 1,
             seq: int = 1) -> Mesh:
    """Convenience: fill `tensor` (or `data`) to consume n_devices."""
    if tensor == 0:
        tensor = n_devices // (stage * expert * seq)
    data = n_devices // (stage * expert * seq * tensor)
    cfg = MeshConfig(data=data, stage=stage, expert=expert, seq=seq, tensor=tensor)
    return make_mesh(cfg, devices=jax.devices()[:n_devices])


def slice_groups(devices: Sequence[jax.Device]) -> dict:
    """Group devices by TPU slice (DCN island). Devices without a
    slice_index (CPU, single-slice) all land in slice 0."""
    groups: dict = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return groups


def make_hybrid_mesh(cfg: MeshConfig,
                     devices: Optional[Sequence[jax.Device]] = None,
                     dcn_axes: Sequence[str] = ("data",)) -> Mesh:
    """Mesh for multi-slice deployments: `dcn_axes` span slices (over
    DCN), every other axis stays inside one slice (over ICI).

    The scaling-book recipe: collectives that run every layer (tensor,
    expert, seq all-reduces / all-to-alls) must ride ICI, so only the
    low-traffic axes — `data` by default, optionally `stage` whose
    ppermute handoff crosses a slice boundary once per microbatch — may
    be placed across slices. Single-slice (or CPU) device sets fall
    back to the plain ICI mesh, so callers can use this unconditionally.
    """
    if devices is None:
        devices = jax.devices()
    groups = slice_groups(devices)
    if len(groups) == 1:
        return make_mesh(cfg, devices)

    sizes = dict(zip(MESH_AXES, cfg.axis_sizes))
    bad = [a for a in dcn_axes if a not in MESH_AXES]
    if bad:
        raise ValueError(f"unknown mesh axes {bad}")
    dcn_shape = [sizes[a] if a in dcn_axes else 1 for a in MESH_AXES]
    ici_shape = [1 if a in dcn_axes else sizes[a] for a in MESH_AXES]
    n_dcn = int(np.prod(dcn_shape))
    per_slice = int(np.prod(ici_shape))
    if n_dcn != len(groups):
        raise ValueError(
            f"dcn axes {tuple(dcn_axes)} have total size {n_dcn} but the "
            f"job spans {len(groups)} slices")
    if any(len(g) != per_slice for g in groups.values()):
        raise ValueError(
            f"each slice must contribute {per_slice} devices "
            f"(got {[len(g) for g in groups.values()]})")
    from jax.experimental import mesh_utils
    dev_array = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=devices,
        allow_split_physical_axes=True)
    return Mesh(dev_array, MESH_AXES)


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host control-plane bringup (SURVEY.md §3 call stack 3).

    On a real pod each host calls this before `make_mesh`; jax.distributed
    handles the DCN rendezvous that NCCL/MPI would in a GPU design. No-op
    when single-process (the common dev/test case).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("BUTTERFLY_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
