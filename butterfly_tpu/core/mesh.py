"""Device mesh bringup.

TPU-native replacement for the reference's planned "communication layer"
bootstrap (/root/reference/CLAUDE.md:20): instead of NCCL communicator
setup, we build a `jax.sharding.Mesh` whose axis order maps parallelism
kinds onto the ICI topology — `tensor` innermost (fastest links, all-reduce
every layer), `data` outermost (least traffic, may cross DCN).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import MESH_AXES, MeshConfig


def make_mesh(cfg: MeshConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh with the canonical axes (data, stage, expert, seq, tensor).

    Axis sizes of 1 are kept (not squeezed) so PartitionSpecs can always
    name every axis; XLA elides collectives over size-1 axes for free.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if cfg.num_devices != n:
        raise ValueError(
            f"MeshConfig wants {cfg.num_devices} devices "
            f"({dict(zip(MESH_AXES, cfg.axis_sizes))}) but {n} are available"
        )
    dev_array = np.asarray(devices).reshape(cfg.axis_sizes)
    return Mesh(dev_array, MESH_AXES)


def local_mesh() -> Mesh:
    """Single-device mesh (all axes size 1) — the CPU/1-chip dev loop."""
    return make_mesh(MeshConfig(), devices=jax.devices()[:1])


def mesh_for(n_devices: int, tensor: int = 0, stage: int = 1, expert: int = 1,
             seq: int = 1) -> Mesh:
    """Convenience: fill `tensor` (or `data`) to consume n_devices."""
    if tensor == 0:
        tensor = n_devices // (stage * expert * seq)
    data = n_devices // (stage * expert * seq * tensor)
    cfg = MeshConfig(data=data, stage=stage, expert=expert, seq=seq, tensor=tensor)
    return make_mesh(cfg, devices=jax.devices()[:n_devices])


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host control-plane bringup (SURVEY.md §3 call stack 3).

    On a real pod each host calls this before `make_mesh`; jax.distributed
    handles the DCN rendezvous that NCCL/MPI would in a GPU design. No-op
    when single-process (the common dev/test case).
    """
    if num_processes is None:
        num_processes = int(os.environ.get("BUTTERFLY_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
