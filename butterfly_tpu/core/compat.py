"""jax API compatibility shims (0.4.x <-> 0.6+).

The seq-parallel paths (parallel/sequence.py, the engines' mesh
contexts, partition.compiled_hlo) were written against the modern
`jax.shard_map` / `jax.set_mesh` surface; the pinned toolchain ships
jax 0.4.37 where both live under different names with slightly
different signatures. Everything mesh-scoped funnels through these two
helpers so the version fork exists in exactly one place:

* `shard_map(f, mesh, in_specs, out_specs, axis_names)` — manual over
  `axis_names` only; other mesh axes stay GSPMD-auto inside the body
  (SP composes with TP). New jax spells that `axis_names=... ,
  check_vma=False`; 0.4.x spells it `auto=<the other axes>,
  check_rep=False` (the SNIPPETS.md kernel-wrapping pattern).
* `mesh_ctx(mesh)` — `with` context making `mesh` ambient for jit
  dispatch: `jax.set_mesh` where it exists, else the Mesh object
  itself (a context manager on 0.4.x).
"""
from __future__ import annotations

import contextlib

import jax


def shard_map(f, mesh, in_specs, out_specs, axis_names=None):
    """Version-portable shard_map over an explicit mesh.

    `axis_names`: the mesh axes the body is MANUAL over (collectives
    may reference them); None = manual over every axis of the mesh.
    Replication of outputs is never checked/inferred (check_vma /
    check_rep False) — out_specs are trusted, as everywhere else in
    this codebase.
    """
    if hasattr(jax, "shard_map"):  # jax >= 0.6
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False, **kw)
    # jax 0.4.x: go FULL manual. The `auto=` partial-manual form exists
    # but its axis_index lowers to a bare partition-id the SPMD
    # partitioner then refuses ("PartitionId instruction is not
    # supported for SPMD partitioning"). Full manual sidesteps the
    # partitioner entirely; axes the caller left auto just see their
    # operands replicated per in_specs — correct, merely unsharded on
    # the old toolchain (the new-API branch keeps them GSPMD-auto).
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def axis_size(axis_name):
    """Static size of a named mesh axis, from inside shard_map.

    `lax.axis_size` is jax >= 0.5; on 0.4.x `psum(1, axis)` constant-
    folds to the same static int.
    """
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def mesh_ctx(mesh):
    """Context manager making `mesh` the ambient mesh (None = no-op)."""
    if mesh is None:
        return contextlib.nullcontext()
    if hasattr(jax, "set_mesh"):  # jax >= 0.6
        return jax.set_mesh(mesh)
    return mesh  # Mesh is a context manager on 0.4.x

