"""Llama-3 model family (BASELINE.json configs[1] 8B TP=8, configs[2] 70B TPxPP).

RMSNorm, RoPE (rotate-half, matching the HF convention so imported
safetensors agree numerically), GQA, SwiGLU, untied lm_head — all expressed
via ModelConfig over the shared functional core in models/common.py.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from butterfly_tpu.core.config import ModelConfig, llama3_8b, llama3_70b  # noqa: F401
from butterfly_tpu.models.common import Model


def model(cfg: ModelConfig | None = None) -> Model:
    return Model(cfg or llama3_8b())


def params_from_hf_state_dict(sd: Dict[str, Any], cfg: ModelConfig) -> Dict:
    """Convert a HF transformers LlamaForCausalLM state_dict to our pytree.

    HF Linear stores weight as [out, in]; our layout is [in, ...out]. The
    q/k/v projections additionally reshape the out axis into (heads, head_dim).
    """
    def g(name):
        t = sd[name]
        return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                          dtype=np.float32)

    L, D = cfg.num_layers, cfg.hidden_size
    Nq, Kv, H = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def stack(fmt, post=lambda a: a):
        return jnp.asarray(np.stack([post(g(fmt.format(i))) for i in range(L)]))

    def proj(n_heads):
        # [out, in] -> [in, heads, head_dim]
        return lambda a: a.T.reshape(D, n_heads, H)

    params = {
        "embed": {"tok": jnp.asarray(g("model.embed_tokens.weight"))},
        "layers": {
            "ln1": {"scale": stack("model.layers.{}.input_layernorm.weight")},
            "ln2": {"scale": stack("model.layers.{}.post_attention_layernorm.weight")},
            "attn": {
                "wq": stack("model.layers.{}.self_attn.q_proj.weight", proj(Nq)),
                "wk": stack("model.layers.{}.self_attn.k_proj.weight", proj(Kv)),
                "wv": stack("model.layers.{}.self_attn.v_proj.weight", proj(Kv)),
                "wo": stack("model.layers.{}.self_attn.o_proj.weight",
                            post=lambda a: a.T.reshape(Nq, H, D)),
            },
            "mlp": {
                "w_gate": stack("model.layers.{}.mlp.gate_proj.weight",
                                post=lambda a: a.T),
                "w_up": stack("model.layers.{}.mlp.up_proj.weight",
                              post=lambda a: a.T),
                "w_down": stack("model.layers.{}.mlp.down_proj.weight",
                                post=lambda a: a.T),
            },
        },
        "final_norm": {"scale": jnp.asarray(g("model.norm.weight"))},
    }
    if "lm_head.weight" in sd:
        params["lm_head"] = jnp.asarray(g("lm_head.weight").T)
    else:  # tied
        params["lm_head"] = jnp.asarray(g("model.embed_tokens.weight").T)
    return params
