"""On-device draft model for speculative serving (ROADMAP item 3).

The PR 9 spec block drafted from prompt-lookup n-grams only — a
model-free source that earns nothing on non-repetitive traffic. This
module supplies the real thing: a small same-architecture draft model,
resident on the same chip, whose per-round forward runs INSIDE the
jitted spec scan (engine/serving.py `_spec_scan`) so drafting never
costs a host trip.

Two ways to get draft weights:

* **Truncated-layer derivation** (`derive_draft_params`): the first
  `draft_layers` layers of the target checkpoint, with the embedding,
  final norm, and unembedding SHARED by reference (same device buffers
  — zero extra HBM for them). Residual-stream architectures make this
  a surprisingly strong free draft: the hidden state after L_d layers
  already points near the full model's output direction, and the
  shared unembed reads it out in the target's own vocabulary geometry
  (the self-speculative family — PAPERS.md arXiv:2305.09781 builds on
  exactly this kind of cheap draft before token trees).
* **Independent narrow checkpoint** (`--draft-ckpt`,
  ckpt.load.load_draft_checkpoint): any HF-format model with the SAME
  vocabulary, loaded through the existing ckpt machinery.

The draft keeps its own KV cache — a contiguous
[L_d, S, W_d, Kv_d, H_d] buffer (models.common.KVCache, so it is the
pool representation already: int8 codes + per-vector scales when
RuntimeConfig.kv_quant="int8") that RIDES THE SPEC BLOCK CARRY. Each
round the γ+1 draft micro-steps write their K/V at the draft length;
after the verify, the length advances by the ACCEPTED count only
(engine/serving.py `_draft_rollback`), so a rejected draft's K/V sits
past the live length — unattendable, and overwritten in place by the
next round's micro-steps, which start exactly at the rolled-back
length. Rollback is exact BY CONSTRUCTION, the same argument as the
PR 12 window's win_len. At every admission the scheduler reseeds the
slot's draft KV from host truth (`ServingEngine.draft_prefill` — one
small batched fresh forward over the gang's prompts), exactly like the
PR 9 history carry, so preemption/readmission can never leave stale
draft state behind.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.engine.sampling import _filter_logits
from butterfly_tpu.models.common import KVCache, forward, init_cache

Params = dict


def resolve_draft_layers(cfg: ModelConfig, draft_layers: int) -> int:
    """Validated truncation depth: `draft_layers` as given, or (when 0,
    the config default) a quarter of the target's depth, floored at 1.
    Must leave the derivation a strict truncation — a draft as deep as
    the target would just run the target twice."""
    if draft_layers < 0:
        raise ValueError(f"draft_layers must be >= 0, got {draft_layers}")
    n = draft_layers if draft_layers > 0 else max(1, cfg.num_layers // 4)
    if not 1 <= n < cfg.num_layers:
        raise ValueError(
            f"draft_layers={draft_layers} invalid for a "
            f"{cfg.num_layers}-layer target: need 1 <= n < num_layers")
    return n


def derive_draft_params(params: Params, cfg: ModelConfig,
                        draft_layers: int) -> Tuple[ModelConfig, Params]:
    """Truncated-layer draft derivation: first `draft_layers` layers of
    the target tree, shared embed/final-norm/unembed.

    Layer-stacked leaves ([L, ...], including quantized {w, scale}
    dicts — every inner array keeps L leading) are sliced
    `[:draft_layers]`; the embedding table, final norm, and LM head are
    the SAME array objects as the target's (no copy, no extra HBM —
    the round-trip test pins identity). Works on float, cast, and int8
    weight trees alike because slicing is dtype-agnostic.
    """
    n = resolve_draft_layers(cfg, draft_layers)
    dcfg = cfg.replace(num_layers=n)
    dparams: Params = {
        "embed": params["embed"],                      # shared, by ref
        "layers": jax.tree.map(lambda a: a[:n], params["layers"]),
        "final_norm": params["final_norm"],            # shared, by ref
    }
    if "lm_head" in params:
        dparams["lm_head"] = params["lm_head"]         # shared, by ref
    return dcfg, dparams


def _pow2(n: int, lo: int, hi: int) -> int:
    """Next power-of-two bucket >= n in [lo, hi] (static-shape cap on
    how many draft-prefill programs ever compile)."""
    b = lo
    while b < min(n, hi):
        b *= 2
    return min(b, hi)


def _draft_prefill_step(cfg: ModelConfig, params, cache: KVCache,
                        tokens, lens, slots):
    """Seed `slots`' draft KV with their prompts: gather the member
    rows' cache slices, run ONE fresh causal forward over the padded
    [M, T] prompt chunk, scatter back. Padding rows carry an
    out-of-range slot id: their gather clamps (reads garbage, unused)
    and their scatter drops (mode="drop"), so they never touch live
    state. Pad positions >= lens write K/V past the seeded length —
    unattendable until the first micro-step overwrites them."""
    quant = cache.quantized
    sub = KVCache(
        k=cache.k[:, slots], v=cache.v[:, slots],
        length=jnp.zeros_like(lens),
        k_scale=cache.k_scale[:, slots] if quant else None,
        v_scale=cache.v_scale[:, slots] if quant else None)
    T = tokens.shape[1]
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], tokens.shape)
    _, sub = forward(params, cfg, tokens, sub, positions, fresh=True)
    k = cache.k.at[:, slots].set(sub.k, mode="drop")
    v = cache.v.at[:, slots].set(sub.v, mode="drop")
    ks, vs = cache.k_scale, cache.v_scale
    if quant:
        ks = ks.at[:, slots].set(sub.k_scale, mode="drop")
        vs = vs.at[:, slots].set(sub.v_scale, mode="drop")
    length = cache.length.at[slots].set(lens, mode="drop")
    return KVCache(k, v, length, ks, vs)


class ModelDraftSource:
    """Draft source backed by a real on-device model (DRAFT_SOURCES
    entry "model", engine/serving.py).

    State is the draft KVCache; `draft()` is pure jax traced inside the
    spec scan (γ autoregressive micro-steps over the draft cache,
    returning the drafted tokens AND their proposal logits so
    `sampling.speculative_accept` can apply the full min(1, p/q)
    rejection-sampling rule instead of the one-hot special case);
    `prefill()` is the host-side admission hook.
    """

    stateful = True

    def __init__(self, cfg: ModelConfig, params, *, num_slots: int,
                 width: int, kv_quant: str = "none"):
        self.cfg = cfg
        self.params = params
        self.num_slots = num_slots
        self.width = width
        self.kv_quant = kv_quant
        self._prefill_prog = jax.jit(
            partial(_draft_prefill_step, cfg), donate_argnums=(1,))

    def init_state(self) -> KVCache:
        """Fresh draft cache: [L_d, S, W_d, Kv_d, H_d] in the pool
        representation (int8 codes + scales iff kv_quant="int8").
        W_d = the serving cache's max_seq plus γ+1 slack so micro-step
        writes can never clamp onto a live entry at the sequence cap."""
        return init_cache(self.cfg, self.num_slots, self.width,
                          quant=self.kv_quant)

    def prefill(self, state: KVCache, slots: np.ndarray, rows: np.ndarray,
                lens: np.ndarray) -> KVCache:
        """Reseed newly admitted slots' draft KV from host truth (the
        same rows the scheduler seeds the token-history carry with —
        prompt + prior output on readmission, WITHOUT the first sampled
        token, which is exactly the d_len = hist_len - 1 invariant:
        the newest token's K/V is the next micro-step's write). Called
        at a full drain barrier only (admission), so no spec block is
        in flight against the donated state."""
        M = len(slots)
        T = _pow2(int(max(1, lens.max())), 16, self.width)
        Mb = _pow2(M, 1, self.num_slots)
        buf = np.zeros((Mb, T), np.int32)
        buf[:M] = rows[:, :T]
        lv = np.zeros((Mb,), np.int32)
        lv[:M] = np.minimum(lens, T)
        # padding rows scatter nowhere: out-of-range slot id + drop mode
        sv = np.full((Mb,), self.num_slots, np.int32)
        sv[:M] = slots
        return self._prefill_prog(self.params, state, jnp.asarray(buf),
                                  jnp.asarray(lv), jnp.asarray(sv))

    def draft(self, hist, hlen, gamma: int, ngram: int, live, state,
              key, temps, top_k: int, top_p: float):
        """γ autoregressive micro-steps over the draft cache — pure
        jax, traced inside the spec scan. Entry invariant:
        state.length == hlen - 1 per live slot (every history token's
        K/V except the newest is in the draft cache). Micro-step j
        consumes the current token (the history tail first, then the
        previous draft), writes its K/V at the draft length, and
        proposes the next token — greedy for temp-0 slots, sampled
        from the SAME temperature/top-k/top-p-filtered distribution
        the accept test scores as q otherwise. A final (γ+1)-th step
        writes the last draft's K/V without proposing, covering the
        all-accepted case; the caller's rollback then lands the length
        anywhere in [hlen-1+1, hlen-1+γ+1] without a gap. Dead slots'
        lengths never advance — their (garbage) writes sit at the
        frozen length, past the live region.

        Returns (drafts [S, γ] int32, q_logits [S, γ, V] — the
        filtered scaled proposal logits speculative_accept consumes —
        and the advanced state, length = base + γ + 1 where live; the
        spec scan rolls it back to base + accepted)."""
        S, H = hist.shape
        dlen0 = state.length
        cur = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
        drafts, qlogs = [], []
        for j in range(gamma + 1):
            logits, state = forward(self.params, self.cfg, cur[:, None],
                                    state)
            # forward advances every row; dead slots stay frozen (their
            # write landed AT the frozen length — garbage past the live
            # region, overwritten by the next live micro-step there)
            state = state._replace(
                length=jnp.where(live, dlen0 + j + 1, dlen0))
            if j == gamma:
                break
            q = logits[:, -1, :]
            scaled = _filter_logits(q / safe_t, top_k, top_p)
            greedy = jnp.argmax(q, axis=-1).astype(jnp.int32)
            drawn = jax.random.categorical(
                jax.random.fold_in(key, j), scaled, axis=-1
            ).astype(jnp.int32)
            nxt = jnp.where(temps > 0, drawn, greedy)
            drafts.append(nxt)
            qlogs.append(scaled)
            cur = nxt
        return (jnp.stack(drafts, axis=1),
                jnp.stack(qlogs, axis=1), state)

    def tree_draft(self, hist, hlen, width: int, depth: int, live, state,
                   key, temps, top_k: int, top_p: float):
        """Token-TREE drafting (ISSUE 19): `depth` micro-steps along the
        PRINCIPAL chain, branching a fan of `width` sibling candidates
        from each step's per-position q. Same KV machinery and entry
        invariant as `draft` (state.length == hlen - 1; micro-step d
        writes the principal's K/V at the draft length; a final extra
        step covers the all-accepted case) — the tree adds only extra
        SAMPLES per step, never extra forwards, because all siblings at
        a depth share the principal's context in the caterpillar
        topology (sampling.tree_principal).

        Per step the fan is drawn from ONE filtered scaled q: sibling 0
        (the principal, which the chain continues through) plus
        width-1 extra i.i.d. categorical draws on stochastic rows —
        the i.i.d. property is what makes the recursive-residual
        acceptance law exact — or the top-`width` distinct tokens on
        greedy rows (index 0 = the raw argmax, so the greedy principal
        chain is byte-identical to `draft`'s).

        Only the principal's K/V enters the draft cache: when the
        verify accepts a non-principal sibling as its DEEPEST node, the
        rolled-back cache holds the principal's K/V at that one
        position instead — bounded one-token context staleness for the
        next round's drafting. Exactness is unaffected (the accept
        test always scores the q the drafter actually sampled from);
        only the hedge's future acceptance rate pays marginally.

        Returns (drafts [S, depth, width] int32, q_logits
        [S, depth, V] — one shared filtered scaled q per fan — and the
        advanced state, length = base + depth + 1 where live)."""
        S, H = hist.shape
        dlen0 = state.length
        cur = jnp.take_along_axis(
            hist, jnp.clip(hlen - 1, 0, H - 1)[:, None], axis=1)[:, 0]
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
        fans, qlogs = [], []
        for d in range(depth + 1):
            logits, state = forward(self.params, self.cfg, cur[:, None],
                                    state)
            state = state._replace(
                length=jnp.where(live, dlen0 + d + 1, dlen0))
            if d == depth:
                break
            q = logits[:, -1, :]
            scaled = _filter_logits(q / safe_t, top_k, top_p)
            _, top_toks = jax.lax.top_k(q, width)  # [S, width], [0]=argmax
            fan = []
            for i in range(width):
                drawn = jax.random.categorical(
                    jax.random.fold_in(key, d * width + i), scaled,
                    axis=-1).astype(jnp.int32)
                fan.append(jnp.where(temps > 0, drawn,
                                     top_toks[:, i].astype(jnp.int32)))
            fans.append(jnp.stack(fan, axis=1))
            qlogs.append(scaled)
            cur = fan[0]  # the chain continues through the principal
        return (jnp.stack(fans, axis=1),
                jnp.stack(qlogs, axis=1), state)
