"""GPT-2 model family (BASELINE.json configs[0]: 124M CPU greedy reference).

The architecture-specific parts (LayerNorm+bias, learned positions, gelu_new,
fused-then-split qkv in HF checkpoints, tied lm_head) are expressed through
ModelConfig flags; the forward pass is models/common.py. This module adds the
HF-weight mapping used by the golden parity tests and the checkpoint importer.
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from butterfly_tpu.core.config import ModelConfig, gpt2_124m  # noqa: F401
from butterfly_tpu.models.common import Model


def model(cfg: ModelConfig | None = None) -> Model:
    return Model(cfg or gpt2_124m())


def params_from_hf_state_dict(sd: Dict[str, Any], cfg: ModelConfig) -> Dict:
    """Convert a HF transformers GPT2LMHeadModel state_dict to our pytree.

    HF GPT-2 uses Conv1D (weight stored [in, out], same orientation as our
    `x @ w` layout) and a fused c_attn producing q|k|v along the out axis.
    Tensors arrive as torch; we convert via numpy. Layer tensors are stacked
    on a leading L axis to match the scan layout.
    """
    def g(name):
        t = sd[name]
        return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                          dtype=np.float32)

    L, D, N, H = cfg.num_layers, cfg.hidden_size, cfg.num_heads, cfg.head_dim

    def stack(fmt, post=lambda a: a):
        return jnp.asarray(np.stack([post(g(fmt.format(i))) for i in range(L)]))

    # fused qkv: [D, 3D] -> three [D, N, H]
    qkv_w = [g(f"transformer.h.{i}.attn.c_attn.weight") for i in range(L)]
    qkv_b = [g(f"transformer.h.{i}.attn.c_attn.bias") for i in range(L)]
    wq = jnp.asarray(np.stack([w[:, :D].reshape(D, N, H) for w in qkv_w]))
    wk = jnp.asarray(np.stack([w[:, D:2 * D].reshape(D, N, H) for w in qkv_w]))
    wv = jnp.asarray(np.stack([w[:, 2 * D:].reshape(D, N, H) for w in qkv_w]))
    bq = jnp.asarray(np.stack([b[:D].reshape(N, H) for b in qkv_b]))
    bk = jnp.asarray(np.stack([b[D:2 * D].reshape(N, H) for b in qkv_b]))
    bv = jnp.asarray(np.stack([b[2 * D:].reshape(N, H) for b in qkv_b]))

    params = {
        "embed": {
            "tok": jnp.asarray(g("transformer.wte.weight")),
            "pos": jnp.asarray(g("transformer.wpe.weight")),
        },
        "layers": {
            "ln1": {"scale": stack("transformer.h.{}.ln_1.weight"),
                    "bias": stack("transformer.h.{}.ln_1.bias")},
            "ln2": {"scale": stack("transformer.h.{}.ln_2.weight"),
                    "bias": stack("transformer.h.{}.ln_2.bias")},
            "attn": {
                "wq": wq, "wk": wk, "wv": wv,
                "bq": bq, "bk": bk, "bv": bv,
                "wo": stack("transformer.h.{}.attn.c_proj.weight",
                            post=lambda a: a.reshape(N, H, D)),
                "bo": stack("transformer.h.{}.attn.c_proj.bias"),
            },
            "mlp": {
                "w_up": stack("transformer.h.{}.mlp.c_fc.weight"),
                "b_up": stack("transformer.h.{}.mlp.c_fc.bias"),
                "w_down": stack("transformer.h.{}.mlp.c_proj.weight"),
                "b_down": stack("transformer.h.{}.mlp.c_proj.bias"),
            },
        },
        "final_norm": {"scale": jnp.asarray(g("transformer.ln_f.weight")),
                       "bias": jnp.asarray(g("transformer.ln_f.bias"))},
    }
    return params
