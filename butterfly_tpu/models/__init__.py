from butterfly_tpu.models import common, gpt2, llama  # noqa: F401
from butterfly_tpu.models.common import init_params, forward, Model  # noqa: F401
