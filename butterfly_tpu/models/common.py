"""Functional transformer core shared by GPT-2 / Llama-3 / Mixtral.

Design (TPU-first, not a torch translation):

* Params are plain pytrees (nested dicts of jnp arrays). No Module system —
  pure functions keep every transform (jit, grad, shard_map, scan) trivially
  applicable, and sharding is attached by the partitioner
  (butterfly_tpu.parallel.partition) as PartitionSpecs over leaf paths.
* Per-layer weights are STACKED on a leading layer axis and the forward pass
  is `lax.scan` over layers: one traced layer body regardless of depth, so a
  70B/80-layer model compiles as fast as a 2-layer one, and pipeline
  parallelism can slice the same stacked leaves into stages.
* The KV cache is a pytree of [L, B, S, Kv, H] arrays updated in-place via
  vmapped `lax.dynamic_update_slice` (XLA DynamicUpdateSlice keeps it
  HBM-resident, per the north star in BASELINE.json).

Capability parity note: this realizes the reference's planned "Distributed
Inference Engine" model side (/root/reference/CLAUDE.md:19,21) for which no
implementation exists (see SURVEY.md §0).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.quant.int8 import qeinsum

Params = Dict[str, Any]


def _cast_float(a: jax.Array, dtype) -> jax.Array:
    """Cast to the compute dtype, leaving integer (e.g. int8) leaves alone."""
    return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a


class KVCache(NamedTuple):
    """Contiguous KV cache: [num_layers, batch, max_seq, num_kv_heads, head_dim].

    `length[b]` = number of tokens already written for sequence b.
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # [B] int32

    @property
    def max_seq(self) -> int:
        return self.k.shape[2]


def init_cache(cfg: ModelConfig, batch: int, max_seq: int,
               dtype: Optional[jnp.dtype] = None) -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        length=jnp.zeros((batch,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * scale.astype(jnp.float32)).astype(dt)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dt)


def gelu_new(x: jax.Array) -> jax.Array:
    """GPT-2's tanh-approximated GELU."""
    c = jnp.sqrt(2.0 / jnp.pi).astype(x.dtype)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


ACTIVATIONS = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "gelu_new": gelu_new,
    "relu": jax.nn.relu,
}


def rope_freqs(cfg: ModelConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for positions [..., T] -> [..., T, head_dim/2], f32."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate-half convention (matches HF Llama so imported weights agree).

    x: [B, T, N, H]; cos/sin: [B, T, half] (or [T, half]).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :].astype(x.dtype)  # broadcast over heads
    sin = sin[..., None, :].astype(x.dtype)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    return jnp.concatenate([r1, r2], axis=-1)


def update_cache_layer(ck: jax.Array, cv: jax.Array, k: jax.Array, v: jax.Array,
                       start: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write k/v [B,T,Kv,H] into cache [B,S,Kv,H] at per-sequence offsets.

    vmapped DynamicUpdateSlice over the batch — stays HBM-resident, no
    host round trip (north-star requirement, BASELINE.json).
    """
    def upd(cache_b, new_b, start_b):
        return lax.dynamic_update_slice(cache_b, new_b, (start_b, 0, 0))

    ck = jax.vmap(upd)(ck, k.astype(ck.dtype), start)
    cv = jax.vmap(upd)(cv, v.astype(cv.dtype), start)
    return ck, cv


def attend(q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array,
           cfg: ModelConfig) -> jax.Array:
    """Grouped-query attention over the (cached) key/value sequence.

    q: [B, T, Nq, H]; k/v: [B, S, Kv, H]; mask: [B, T, S] bool (True=attend).
    Returns [B, T, Nq, H]. Softmax in f32 for stability.
    """
    B, T, Nq, H = q.shape
    S = k.shape[1]
    Kv = k.shape[2]
    G = Nq // Kv
    q = q.reshape(B, T, Kv, G, H)
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    scores = jnp.einsum("btkgh,bskh->bktgs", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores * scale
    scores = jnp.where(mask[:, None, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bktgs,bskh->btkgh", probs.astype(v.dtype), v)
    return out.reshape(B, T, Nq, H)


# ---------------------------------------------------------------------------
# Layer bodies
# ---------------------------------------------------------------------------

def qkv_proj(x: jax.Array, p: Params, cfg: ModelConfig,
             cos: jax.Array, sin: jax.Array
             ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """QKV projections (+bias, +rope). x: [B,T,D] -> q [B,T,Nq,H],
    k/v [B,T,Kv,H]. Shared by the contiguous and paged attention paths."""
    dt = x.dtype
    q = qeinsum("btd,dnh->btnh", x, p["wq"], dt)
    k = qeinsum("btd,dkh->btkh", x, p["wk"], dt)
    v = qeinsum("btd,dkh->btkh", x, p["wv"], dt)
    if cfg.use_bias:
        q = q + p["bq"]
        k = k + p["bk"]
        v = v + p["bv"]
    if cfg.pos_embedding == "rope":
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    return q, k, v


def attn_output(out: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Output projection of the attention sublayer. out: [B,T,Nq,H]."""
    out = qeinsum("btnh,nhd->btd", out, p["wo"], out.dtype)
    if cfg.use_bias:
        out = out + p["bo"]
    return out


def attention_block(x: jax.Array, p: Params, cfg: ModelConfig,
                    ck: jax.Array, cv: jax.Array,
                    positions: jax.Array, mask: jax.Array,
                    cos: jax.Array, sin: jax.Array,
                    fresh: bool = False
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One attention sublayer with contiguous-cache update.

    x: [B,T,D]; ck/cv: [B,S,Kv,H]; positions: [B,T]; mask: [B,T,S].
    `fresh` (static) asserts the cache holds nothing before this call
    (positions start at 0) — required to take the flash path, which
    attends only over the freshly projected K/V. Warm multi-token calls
    (chunked prefill / continuation) fall back to dense cache attention
    even when cfg.attn_impl == "flash", so prior context is never
    silently dropped.
    """
    q, k, v = qkv_proj(x, p, cfg, cos, sin)
    start = positions[:, 0]  # write offset per sequence
    ck, cv = update_cache_layer(ck, cv, k, v, start)
    out = None
    if cfg.attn_impl == "flash" and x.shape[1] > 1 and fresh:
        from butterfly_tpu.ops.flash_attention import flash_attention_sharded
        # None = no mesh axis can shard the kernel operands; use dense.
        out = flash_attention_sharded(q, k, v, causal=True)
    if out is None:
        out = attend(q, ck, cv, mask, cfg)
    return attn_output(out, p, cfg), ck, cv


def mlp_block(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    act = ACTIVATIONS[cfg.act]
    dt = x.dtype
    if cfg.arch == "gpt2":
        h = qeinsum("btd,df->btf", x, p["w_up"], dt)
        h = act(h + p["b_up"])
        out = qeinsum("btf,fd->btd", h, p["w_down"], dt)
        return out + p["b_down"]
    # llama-style gated SwiGLU
    g = qeinsum("btd,df->btf", x, p["w_gate"], dt)
    u = qeinsum("btd,df->btf", x, p["w_up"], dt)
    h = act(g) * u
    return qeinsum("btf,fd->btd", h, p["w_down"], dt)


def route_tokens(x: jax.Array, router_w: jax.Array,
                 k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k MoE routing: f32 logits -> (gates [.., k], expert idx [.., k]).

    Softmax is over the SELECTED k (Mixtral convention). The single
    definition shared by the dense block and both EP dispatch paths —
    their exact-parity contract depends on byte-identical routing.
    """
    logits = jnp.einsum("btd,de->bte", x, router_w).astype(jnp.float32)
    gates, idx = lax.top_k(logits, k)
    return jax.nn.softmax(gates, axis=-1), idx


def moe_block(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Dense-compute MoE (every expert sees every token, masked by router).

    The expert-parallel all_to_all path lives in parallel/expert.py; this
    dense form is the single-device reference and the EP fallback.
    """
    B, T, D = x.shape
    weights, idx = route_tokens(x, p["router"], cfg.num_experts_per_tok)
    onehot = jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)  # [B,T,k,E]
    comb = jnp.einsum("btk,btke->bte", weights, onehot)  # [B,T,E]

    act = ACTIVATIONS[cfg.act]
    dt = x.dtype
    g = qeinsum("btd,edf->ebtf", x, p["w_gate"], dt)
    u = qeinsum("btd,edf->ebtf", x, p["w_up"], dt)
    h = act(g) * u
    y = qeinsum("ebtf,efd->ebtd", h, p["w_down"], dt)
    return jnp.einsum("ebtd,bte->btd", y, comb.astype(y.dtype))


def pre_norm(x: jax.Array, norm_p: Params, cfg: ModelConfig) -> jax.Array:
    """The arch's norm (LayerNorm for gpt2, RMSNorm otherwise)."""
    if cfg.arch == "gpt2":
        return layer_norm(x, norm_p["scale"], norm_p["bias"], cfg.norm_eps)
    return rms_norm(x, norm_p["scale"], cfg.norm_eps)


def ffn_block(h: jax.Array, lp: Params, cfg: ModelConfig) -> jax.Array:
    """FFN dispatch shared by every forward variant (contiguous, paged,
    pipeline, sequence-parallel): dense MLP, dense MoE, or EP MoE per
    cfg — one definition so the variants can't drift."""
    if cfg.is_moe:
        if cfg.moe_impl == "ep":
            from butterfly_tpu.parallel.expert import moe_block_ep
            return moe_block_ep(h, lp["moe"], cfg)
        return moe_block(h, lp["moe"], cfg)
    return mlp_block(h, lp["mlp"], cfg)


def transformer_layer(x: jax.Array, lp: Params, cfg: ModelConfig,
                      ck: jax.Array, cv: jax.Array,
                      positions: jax.Array, mask: jax.Array,
                      cos: jax.Array, sin: jax.Array,
                      fresh: bool = False
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pre-norm residual block: x + attn(norm(x)); x + ffn(norm(x))."""
    h = pre_norm(x, lp["ln1"], cfg)
    attn_out, ck, cv = attention_block(h, lp["attn"], cfg, ck, cv,
                                       positions, mask, cos, sin, fresh)
    x = x + attn_out
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    return x, ck, cv


# ---------------------------------------------------------------------------
# Full forward
# ---------------------------------------------------------------------------

def make_mask(positions: jax.Array, S: int) -> jax.Array:
    """Causal mask over the cache: [B,T,S], True where query may attend.

    A query at absolute position p attends to cache slots j <= p. Slots
    beyond the written region have j > p and are excluded automatically
    (new tokens are written into the cache before attending).
    """
    j = jnp.arange(S)[None, None, :]
    return j <= positions[:, :, None]


def embed_tokens(params: Params, cfg: ModelConfig, tokens: jax.Array,
                 positions: jax.Array
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token (+pos) embedding. Returns (x [B,T,D], cos, sin)."""
    B, T = tokens.shape
    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["embed"]["tok"].astype(compute_dtype)[tokens]
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["pos"].astype(compute_dtype)[positions]
        cos = sin = jnp.zeros((B, T, cfg.head_dim // 2), jnp.float32)
    else:
        cos, sin = rope_freqs(cfg, positions)
    return x, cos, sin


def scan_layers(layer_params: Params, cfg: ModelConfig, x: jax.Array,
                k: jax.Array, v: jax.Array, positions: jax.Array,
                mask: jax.Array, cos: jax.Array, sin: jax.Array,
                fresh: bool = False
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """lax.scan of transformer_layer over layer-stacked leaves.

    Works on any leading-layer-count slice (full model, or one pipeline
    stage's slice — parallel/pipeline.py scans each stage's local layers
    with this same body). Returns (x, new_k, new_v).
    """
    compute_dtype = jnp.dtype(cfg.dtype)

    def body(x, scanned):
        lp, ck, cv = scanned
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        x, ck, cv = transformer_layer(x, lp, cfg, ck, cv,
                                      positions, mask, cos, sin, fresh)
        return x, (ck, cv)

    x, (new_k, new_v) = lax.scan(body, x, (layer_params, k, v))
    return x, new_k, new_v


def final_logits(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    """Final norm + LM head. Returns logits [B,T,V] float32."""
    compute_dtype = jnp.dtype(cfg.dtype)
    if cfg.arch == "gpt2":
        x = layer_norm(x, params["final_norm"]["scale"],
                       params["final_norm"]["bias"], cfg.norm_eps)
    else:
        x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)

    if cfg.tie_embeddings:
        logits = jnp.einsum("btd,vd->btv", x,
                            params["embed"]["tok"].astype(compute_dtype))
    else:
        logits = qeinsum("btd,dv->btv", x, params["lm_head"], compute_dtype)
    return logits.astype(jnp.float32)


def decode_attend(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                  ck: jax.Array, cv: jax.Array, start: jax.Array,
                  cfg: ModelConfig) -> jax.Array:
    """One-token attention over (old cache) + (the token itself).

    The general path writes K/V into the cache BEFORE attending, which
    forces a per-layer scattered cache update inside the layer scan — 2L
    batched-dynamic-slice scatters per decode step, the dominant cost of
    the decode loop at serving batch sizes (measured on v5e). Attending
    over the unmodified cache (positions < start, no write yet) plus an
    explicit self-attention term is mathematically identical for causal
    decode and lets the caller write ALL layers' new K/V in one batched
    update after the scan (see _decode_forward).

    q [B,1,Nq,H]; k_new/v_new [B,1,Kv,H]; ck/cv [B,S,Kv,H]; start [B].
    """
    B, _, Nq, H = q.shape
    S = ck.shape[1]
    Kv = k_new.shape[2]
    G = Nq // Kv
    qg = q.reshape(B, Kv, G, H)
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    s_c = jnp.einsum("bkgh,bskh->bkgs", qg, ck,
                     preferred_element_type=jnp.float32) * scale
    older = jnp.arange(S)[None, :] < start[:, None]          # strictly past
    s_c = jnp.where(older[:, None, None, :], s_c, -1e30)
    s_self = jnp.sum(qg.astype(jnp.float32) *
                     k_new.reshape(B, Kv, 1, H).astype(jnp.float32),
                     axis=-1, keepdims=True) * scale          # [B,Kv,G,1]
    s = jnp.concatenate([s_c, s_self], axis=-1)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgs,bskh->bkgh", p[..., :S].astype(cv.dtype), cv)
    out = out + p[..., S:].astype(v_new.dtype) * v_new.reshape(B, Kv, 1, H)
    return out.reshape(B, 1, Nq, H)


def _decode_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                    cache: KVCache, positions: jax.Array
                    ) -> Tuple[jax.Array, KVCache]:
    """Single-token decode step with ONE batched cache write.

    The layer scan attends via decode_attend (old cache + self term) and
    emits each layer's fresh K/V as stacked scan outputs; the cache is
    then updated for every layer at once with a single vmapped
    dynamic-update-slice — O(1) update ops per step instead of O(L).
    """
    B = tokens.shape[0]
    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    start = positions[:, 0]
    compute_dtype = jnp.dtype(cfg.dtype)

    # scan reads each layer's cache slice as an input (no carry update)
    def layer(x, scanned):
        lp, ck, cv = scanned
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
        out = decode_attend(q, k, v, ck, cv, start, cfg)
        x = x + attn_output(out, lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        return x, (k.astype(ck.dtype), v.astype(cv.dtype))

    x, (ks, vs) = lax.scan(layer, x, (params["layers"], cache.k, cache.v))

    def upd(c_b, n_b, s_b):  # [L,S,Kv,H] <- [L,1,Kv,H] at (0, s_b, 0, 0)
        return lax.dynamic_update_slice(c_b, n_b, (0, s_b, 0, 0))

    new_k = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.k, ks, start)
    new_v = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.v, vs, start)
    logits = final_logits(params, cfg, x)
    return logits, KVCache(new_k, new_v, cache.length + 1)


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            cache: KVCache, positions: Optional[jax.Array] = None,
            fresh: bool = False) -> Tuple[jax.Array, KVCache]:
    """Run the model over `tokens` [B,T], reading/updating `cache`.

    positions defaults to cache.length[:,None] + arange(T) (append).
    `fresh` (static) = the cache is empty and positions start at 0; only
    then may the flash prefill kernel be used (see attention_block).
    Single-token warm calls take the decode fast path (_decode_forward:
    deferred one-shot cache write). Returns (logits [B,T,V] float32,
    updated cache).
    """
    B, T = tokens.shape
    if positions is None:
        positions = cache.length[:, None] + jnp.arange(T)[None, :]
    if T == 1 and not fresh:
        return _decode_forward(params, cfg, tokens, cache, positions)

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)
    x, new_k, new_v = scan_layers(params["layers"], cfg, x, cache.k, cache.v,
                                  positions, mask, cos, sin, fresh)
    logits = final_logits(params, cfg, x)
    new_len = cache.length + T
    return logits, KVCache(new_k, new_v, new_len)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random init (normal, 0.02 std — GPT-2 style) in cfg.param_dtype."""
    pdt = jnp.dtype(cfg.param_dtype)
    L, D, Nq, Kv, H, F, V = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                             cfg.num_kv_heads, cfg.head_dim,
                             cfg.intermediate_size, cfg.vocab_size)
    keys = iter(jax.random.split(key, 32))

    def w(k, *shape, std=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(pdt)

    layers: Params = {
        "ln1": {"scale": jnp.ones((L, D), pdt)},
        "ln2": {"scale": jnp.ones((L, D), pdt)},
        "attn": {
            "wq": w(next(keys), L, D, Nq, H),
            "wk": w(next(keys), L, D, Kv, H),
            "wv": w(next(keys), L, D, Kv, H),
            "wo": w(next(keys), L, Nq, H, D),
        },
    }
    if cfg.use_bias:
        layers["ln1"]["bias"] = jnp.zeros((L, D), pdt)
        layers["ln2"]["bias"] = jnp.zeros((L, D), pdt)
        layers["attn"].update(
            bq=jnp.zeros((L, Nq, H), pdt), bk=jnp.zeros((L, Kv, H), pdt),
            bv=jnp.zeros((L, Kv, H), pdt), bo=jnp.zeros((L, D), pdt),
        )
    if cfg.is_moe:
        E = cfg.num_experts
        layers["moe"] = {
            "router": w(next(keys), L, D, E),
            "w_gate": w(next(keys), L, E, D, F),
            "w_up": w(next(keys), L, E, D, F),
            "w_down": w(next(keys), L, E, F, D),
        }
    elif cfg.arch == "gpt2":
        layers["mlp"] = {
            "w_up": w(next(keys), L, D, F), "b_up": jnp.zeros((L, F), pdt),
            "w_down": w(next(keys), L, F, D), "b_down": jnp.zeros((L, D), pdt),
        }
    else:
        layers["mlp"] = {
            "w_gate": w(next(keys), L, D, F),
            "w_up": w(next(keys), L, D, F),
            "w_down": w(next(keys), L, F, D),
        }

    params: Params = {
        "embed": {"tok": w(next(keys), V, D)},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((D,), pdt)},
    }
    if cfg.pos_embedding == "learned":
        params["embed"]["pos"] = w(next(keys), cfg.max_seq_len, D)
    if cfg.arch == "gpt2":
        params["final_norm"]["bias"] = jnp.zeros((D,), pdt)
    if not cfg.tie_embeddings:
        params["lm_head"] = w(next(keys), D, V)
    return params


@dataclasses.dataclass(frozen=True)
class Model:
    """Thin handle bundling a config with the functional API."""

    cfg: ModelConfig

    def init(self, key: jax.Array) -> Params:
        return init_params(self.cfg, key)

    def init_cache(self, batch: int, max_seq: int, dtype=None) -> KVCache:
        return init_cache(self.cfg, batch, max_seq, dtype)

    def __call__(self, params: Params, tokens: jax.Array, cache: KVCache,
                 positions: Optional[jax.Array] = None):
        return forward(params, self.cfg, tokens, cache, positions)
