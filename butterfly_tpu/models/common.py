"""Functional transformer core shared by GPT-2 / Llama-3 / Mixtral.

Design (TPU-first, not a torch translation):

* Params are plain pytrees (nested dicts of jnp arrays). No Module system —
  pure functions keep every transform (jit, grad, shard_map, scan) trivially
  applicable, and sharding is attached by the partitioner
  (butterfly_tpu.parallel.partition) as PartitionSpecs over leaf paths.
* Per-layer weights are STACKED on a leading layer axis and the forward pass
  is `lax.scan` over layers: one traced layer body regardless of depth, so a
  70B/80-layer model compiles as fast as a 2-layer one, and pipeline
  parallelism can slice the same stacked leaves into stages.
* The KV cache is a pytree of [L, B, S, Kv, H] arrays updated in-place via
  vmapped `lax.dynamic_update_slice` (XLA DynamicUpdateSlice keeps it
  HBM-resident, per the north star in BASELINE.json).

Capability parity note: this realizes the reference's planned "Distributed
Inference Engine" model side (/root/reference/CLAUDE.md:19,21) for which no
implementation exists (see SURVEY.md §0).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from butterfly_tpu.core.config import ModelConfig
# Module-level, deliberately: attention_block runs INSIDE traced code and
# a lazy in-function import executes on every trace — the same per-trace
# tax PR 12's quantize_kv hoist removed from cache/paged.py. No cycle:
# ops.flash_attention imports nothing project-local at module level.
from butterfly_tpu.ops.flash_attention import flash_attention_sharded
from butterfly_tpu.quant.int8 import qeinsum

Params = Dict[str, Any]


def _cast_float(a: jax.Array, dtype) -> jax.Array:
    """Cast to the compute dtype, leaving integer (e.g. int8) leaves alone."""
    return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a


class KVCache(NamedTuple):
    """Contiguous KV cache: [num_layers, batch, max_seq, num_kv_heads, head_dim].

    `length[b]` = number of tokens already written for sequence b.

    int8 mode (init_cache(quant="int8")): k/v hold int8 codes in
    [L, B, Kv, S, H] order and k_scale/v_scale [L,B,Kv,S] hold one f32
    scale per stored vector (absmax over head_dim / 127). Decode streams
    half the cache bytes from HBM — the dominant term of the
    bandwidth-bound decode loop at serving batch sizes; dequantization
    is fused into the attention dots (scores scale output-side, value
    scale folded into the probs), so no bf16 copy of the cache ever
    materializes. The dim order differs from the float cache
    deliberately: TPU tiles pad the two minor dims ((32,128) for int8,
    (8,128) for f32), so Kv=8 minor would inflate physical HBM 4x for
    the codes and 16x for the scales; with (S,H) and (Kv,S) minor there
    is no padding and each (b,kv) attention read is one contiguous
    [S,H] tile run.
    """

    k: jax.Array
    v: jax.Array
    length: jax.Array  # [B] int32
    k_scale: Optional[jax.Array] = None  # [L,B,Kv,S] f32 iff k is int8
    v_scale: Optional[jax.Array] = None

    @property
    def max_seq(self) -> int:
        return self.k.shape[3] if self.quantized else self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(cfg: ModelConfig, batch: int, max_seq: int,
               dtype: Optional[jnp.dtype] = None,
               quant: str = "none") -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim)
    if quant == "int8":
        qshape = (cfg.num_layers, batch, cfg.num_kv_heads, max_seq,
                  cfg.head_dim)
        return KVCache(
            k=jnp.zeros(qshape, jnp.int8),
            v=jnp.zeros(qshape, jnp.int8),
            length=jnp.zeros((batch,), jnp.int32),
            k_scale=jnp.zeros(qshape[:-1], jnp.float32),
            v_scale=jnp.zeros(qshape[:-1], jnp.float32),
        )
    if quant != "none":
        raise ValueError(f"unknown kv quant {quant!r}")
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        length=jnp.zeros((batch,), jnp.int32),
    )


def quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-vector int8 quantization over the last (head_dim) axis.

    x [..., H] float -> (codes [..., H] int8, scale [...] f32) with
    x ~= codes * scale. Zero vectors get scale 1 (codes all 0).
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return jnp.clip(codes, -127, 127).astype(jnp.int8), scale


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (x * scale.astype(jnp.float32)).astype(dt)


def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float) -> jax.Array:
    dt = x.dtype
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dt)


def gelu_new(x: jax.Array) -> jax.Array:
    """GPT-2's tanh-approximated GELU."""
    c = jnp.sqrt(2.0 / jnp.pi).astype(x.dtype)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


ACTIVATIONS = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "gelu_new": gelu_new,
    "relu": jax.nn.relu,
}


def rope_freqs(cfg: ModelConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for positions [..., T] -> [..., T, head_dim/2], f32."""
    half = cfg.head_dim // 2
    inv_freq = 1.0 / (cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate-half convention (matches HF Llama so imported weights agree).

    x: [B, T, N, H]; cos/sin: [B, T, half] (or [T, half]).
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :].astype(x.dtype)  # broadcast over heads
    sin = sin[..., None, :].astype(x.dtype)
    r1 = x1 * cos - x2 * sin
    r2 = x2 * cos + x1 * sin
    return jnp.concatenate([r1, r2], axis=-1)


def update_cache_layer(ck: jax.Array, cv: jax.Array, k: jax.Array, v: jax.Array,
                       start: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Write k/v [B,T,Kv,H] into cache [B,S,Kv,H] at per-sequence offsets.

    vmapped DynamicUpdateSlice over the batch — stays HBM-resident, no
    host round trip (north-star requirement, BASELINE.json).
    """
    def upd(cache_b, new_b, start_b):
        return lax.dynamic_update_slice(cache_b, new_b, (start_b, 0, 0))

    ck = jax.vmap(upd)(ck, k.astype(ck.dtype), start)
    cv = jax.vmap(upd)(cv, v.astype(cv.dtype), start)
    return ck, cv


def update_cache_layer_q(ck, cv, k_s, v_s, k, v, start):
    """int8 twin of update_cache_layer: quantize then write codes +
    scales. Cache layout is [B,Kv,S,H] / scales [B,Kv,S] (see KVCache);
    k/v arrive as [B,T,Kv,H]. Returns (ck, cv, k_s, v_s)."""
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)

    def upd(cache_b, new_b, start_b):  # [Kv,S,H] <- [Kv,T,H] at (0,s,0)
        return lax.dynamic_update_slice(cache_b, new_b, (0, start_b, 0))

    def upd_s(s_b, new_b, start_b):    # [Kv,S] <- [Kv,T] at (0,s)
        return lax.dynamic_update_slice(s_b, new_b, (0, start_b))

    ck = jax.vmap(upd)(ck, kq.transpose(0, 2, 1, 3), start)
    cv = jax.vmap(upd)(cv, vq.transpose(0, 2, 1, 3), start)
    k_s = jax.vmap(upd_s)(k_s, ks.transpose(0, 2, 1), start)
    v_s = jax.vmap(upd_s)(v_s, vs.transpose(0, 2, 1), start)
    return ck, cv, k_s, v_s


def attend(q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array,
           cfg: ModelConfig, k_scale: Optional[jax.Array] = None,
           v_scale: Optional[jax.Array] = None) -> jax.Array:
    """Grouped-query attention over the (cached) key/value sequence.

    q: [B, T, Nq, H]; k/v: [B, S, Kv, H]; mask: [B, T, S] bool (True=attend).
    Returns [B, T, Nq, H]. Softmax in f32 for stability.

    int8 cache: k/v are codes in [B,Kv,S,H] order and k_scale/v_scale
    [B,Kv,S] their per-vector scales. The convert feeds the dot
    directly (only int8 bytes stream from HBM); the K scale is constant
    over the contracted head_dim so it applies to the scores
    output-side, and the V scale varies along the contracted S so it
    folds into the probs.
    """
    B, T, Nq, H = q.shape
    quant = k_scale is not None
    S = k.shape[2] if quant else k.shape[1]
    Kv = k.shape[1] if quant else k.shape[2]
    G = Nq // Kv
    q = q.reshape(B, T, Kv, G, H)
    compute = q.dtype
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    k_eq = "bksh" if quant else "bskh"
    scores = jnp.einsum(f"btkgh,{k_eq}->bktgs", q, _cast_float(k, compute),
                        preferred_element_type=jnp.float32)
    if quant:
        scores = scores * k_scale[:, :, None, None, :]
    scores = scores * scale
    scores = jnp.where(mask[:, None, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if quant:
        probs = probs * v_scale[:, :, None, None, :]
    out = jnp.einsum(f"bktgs,{k_eq}->btkgh", probs.astype(compute),
                     _cast_float(v, compute))
    return out.reshape(B, T, Nq, H)


# ---------------------------------------------------------------------------
# Layer bodies
# ---------------------------------------------------------------------------

def qkv_proj(x: jax.Array, p: Params, cfg: ModelConfig,
             cos: jax.Array, sin: jax.Array
             ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """QKV projections (+bias, +rope). x: [B,T,D] -> q [B,T,Nq,H],
    k/v [B,T,Kv,H]. Shared by the contiguous and paged attention paths."""
    dt = x.dtype
    q = qeinsum("btd,dnh->btnh", x, p["wq"], dt)
    k = qeinsum("btd,dkh->btkh", x, p["wk"], dt)
    v = qeinsum("btd,dkh->btkh", x, p["wv"], dt)
    if cfg.use_bias:
        q = q + p["bq"]
        k = k + p["bk"]
        v = v + p["bv"]
    if cfg.pos_embedding == "rope":
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
    return q, k, v


def attn_output(out: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Output projection of the attention sublayer. out: [B,T,Nq,H]."""
    out = qeinsum("btnh,nhd->btd", out, p["wo"], out.dtype)
    if cfg.use_bias:
        out = out + p["bo"]
    return out


def attention_block(x: jax.Array, p: Params, cfg: ModelConfig,
                    ck: jax.Array, cv: jax.Array,
                    positions: jax.Array, mask: jax.Array,
                    cos: jax.Array, sin: jax.Array,
                    fresh: bool = False,
                    k_s: Optional[jax.Array] = None,
                    v_s: Optional[jax.Array] = None):
    """One attention sublayer with contiguous-cache update.

    x: [B,T,D]; ck/cv: [B,S,Kv,H]; positions: [B,T]; mask: [B,T,S].
    `fresh` (static) asserts positions start at 0 and nothing LIVE
    precedes this call's tokens — the flash path then attends only over
    the freshly projected K/V. The cache buffers may still hold stale
    bytes from a recycled pool (engine cache reuse): correctness must
    come from position masking and overwrite-before-attend, never from
    assuming zeroed buffers. Warm multi-token calls (chunked prefill /
    continuation / prefix-hit resume) take the kernel too under
    cfg.attn_impl == "flash" (ISSUE 13): the cache rides in as the
    kernel's cached-prefix segment, count-masked per row at `start`, so
    warm prefill stops paying the dense O(T*S) fallback; dense attend
    stays as the non-flash path and the parity reference.

    int8 cache: pass codes ck/cv [B,Kv,S,H] + scales k_s/v_s [B,Kv,S];
    the return gains the updated scales — (out, ck, cv, k_s, v_s)
    instead of (out, ck, cv).

    ck is None (requires `fresh`): NO-CACHE mode for the fresh-prefill
    fast path (_fresh_prefill_forward) — nothing is written, attention
    runs over the just-projected K/V (flash, or a dense causal fallback
    over the same values), and the raw k/v come back so the caller can
    write the pools itself: returns (out, k, v).
    """
    q, k, v = qkv_proj(x, p, cfg, cos, sin)
    if ck is None:
        assert fresh, "no-cache attention_block is fresh-prefill only"
        out = None
        if cfg.attn_impl == "flash" and x.shape[1] > 1:
            out = flash_attention_sharded(q, k, v, causal=True)
        if out is None:
            out = attend(q, k, v, mask, cfg)
        return attn_output(out, p, cfg), k, v
    start = positions[:, 0]  # write offset per sequence
    if k_s is not None:  # int8 cache: write codes + scales
        ck, cv, k_s, v_s = update_cache_layer_q(ck, cv, k_s, v_s, k, v,
                                                start)
    else:
        ck, cv = update_cache_layer(ck, cv, k, v, start)
    out = None
    if cfg.attn_impl == "flash" and x.shape[1] > 1:
        # None = no mesh axis can shard the kernel operands; use dense.
        # (Fresh prefill attends over the just-projected bf16 K/V, so the
        # kernel path is identical for int8 caches.)
        if fresh:
            out = flash_attention_sharded(q, k, v, causal=True)
        else:
            # warm chunk (ISSUE 13): the kernel attends the cache as a
            # prefix segment count-masked at `start` (the chunk's own
            # just-written copy sits at >= start, excluded) plus the
            # fresh chunk. int8 caches mirror the written representation
            # for the chunk itself — quantize-dequantize the fresh K/V —
            # so the operand set is element-wise identical to what the
            # dense path reads back, the byte-parity argument.
            kf, vf = k, v
            if k_s is not None:
                kq, ksc = quantize_kv(k)
                vq, vsc = quantize_kv(v)
                kf = (kq.astype(jnp.float32)
                      * ksc[..., None]).astype(k.dtype)
                vf = (vq.astype(jnp.float32)
                      * vsc[..., None]).astype(v.dtype)
            out = flash_attention_sharded(
                q, kf, vf, causal=True, prefix_k=ck, prefix_v=cv,
                prefix_len=start, prefix_k_scale=k_s, prefix_v_scale=v_s)
    if out is None:
        out = attend(q, ck, cv, mask, cfg, k_s, v_s)
    if k_s is not None:
        return attn_output(out, p, cfg), ck, cv, k_s, v_s
    return attn_output(out, p, cfg), ck, cv


def mlp_block(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    act = ACTIVATIONS[cfg.act]
    dt = x.dtype
    if cfg.arch == "gpt2":
        h = qeinsum("btd,df->btf", x, p["w_up"], dt)
        h = act(h + p["b_up"])
        out = qeinsum("btf,fd->btd", h, p["w_down"], dt)
        return out + p["b_down"]
    # llama-style gated SwiGLU
    g = qeinsum("btd,df->btf", x, p["w_gate"], dt)
    u = qeinsum("btd,df->btf", x, p["w_up"], dt)
    h = act(g) * u
    return qeinsum("btf,fd->btd", h, p["w_down"], dt)


def route_tokens(x: jax.Array, router_w: jax.Array,
                 k: int) -> Tuple[jax.Array, jax.Array]:
    """Top-k MoE routing: f32 logits -> (gates [.., k], expert idx [.., k]).

    Softmax is over the SELECTED k (Mixtral convention). The single
    definition shared by the dense block and both EP dispatch paths —
    their exact-parity contract depends on byte-identical routing.
    """
    logits = jnp.einsum("btd,de->bte", x, router_w).astype(jnp.float32)
    gates, idx = lax.top_k(logits, k)
    return jax.nn.softmax(gates, axis=-1), idx


def moe_block(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Dense-compute MoE (every expert sees every token, masked by router).

    The expert-parallel all_to_all path lives in parallel/expert.py; this
    dense form is the single-device reference and the EP fallback.
    """
    B, T, D = x.shape
    weights, idx = route_tokens(x, p["router"], cfg.num_experts_per_tok)
    onehot = jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.float32)  # [B,T,k,E]
    comb = jnp.einsum("btk,btke->bte", weights, onehot)  # [B,T,E]

    act = ACTIVATIONS[cfg.act]
    dt = x.dtype
    g = qeinsum("btd,edf->ebtf", x, p["w_gate"], dt)
    u = qeinsum("btd,edf->ebtf", x, p["w_up"], dt)
    h = act(g) * u
    y = qeinsum("ebtf,efd->ebtd", h, p["w_down"], dt)
    return jnp.einsum("ebtd,bte->btd", y, comb.astype(y.dtype))


def pre_norm(x: jax.Array, norm_p: Params, cfg: ModelConfig) -> jax.Array:
    """The arch's norm (LayerNorm for gpt2, RMSNorm otherwise)."""
    if cfg.arch == "gpt2":
        return layer_norm(x, norm_p["scale"], norm_p["bias"], cfg.norm_eps)
    return rms_norm(x, norm_p["scale"], cfg.norm_eps)


def ffn_block(h: jax.Array, lp: Params, cfg: ModelConfig) -> jax.Array:
    """FFN dispatch shared by every forward variant (contiguous, paged,
    pipeline, sequence-parallel): dense MLP, dense MoE, or EP MoE per
    cfg — one definition so the variants can't drift."""
    if cfg.is_moe:
        if cfg.moe_impl == "ep":
            from butterfly_tpu.parallel.expert import moe_block_ep
            return moe_block_ep(h, lp["moe"], cfg)
        return moe_block(h, lp["moe"], cfg)
    return mlp_block(h, lp["mlp"], cfg)


def transformer_layer(x: jax.Array, lp: Params, cfg: ModelConfig,
                      ck: jax.Array, cv: jax.Array,
                      positions: jax.Array, mask: jax.Array,
                      cos: jax.Array, sin: jax.Array,
                      fresh: bool = False,
                      k_s: Optional[jax.Array] = None,
                      v_s: Optional[jax.Array] = None):
    """Pre-norm residual block: x + attn(norm(x)); x + ffn(norm(x)).

    Returns (x, ck, cv), or (x, ck, cv, k_s, v_s) with an int8 cache;
    in attention_block's no-cache fresh mode (ck None), (x, k, v) with
    the layer's raw projected K/V.
    """
    h = pre_norm(x, lp["ln1"], cfg)
    attn_out, *rest = attention_block(
        h, lp["attn"], cfg, ck, cv, positions, mask, cos, sin, fresh,
        k_s, v_s)
    x = x + attn_out
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    return (x, *rest)


# ---------------------------------------------------------------------------
# Full forward
# ---------------------------------------------------------------------------

def make_mask(positions: jax.Array, S: int) -> jax.Array:
    """Causal mask over the cache: [B,T,S], True where query may attend.

    A query at absolute position p attends to cache slots j <= p. Slots
    beyond the written region have j > p and are excluded automatically
    (new tokens are written into the cache before attending).
    """
    j = jnp.arange(S)[None, None, :]
    return j <= positions[:, :, None]


def embed_tokens(params: Params, cfg: ModelConfig, tokens: jax.Array,
                 positions: jax.Array
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Token (+pos) embedding. Returns (x [B,T,D], cos, sin)."""
    B, T = tokens.shape
    compute_dtype = jnp.dtype(cfg.dtype)
    x = params["embed"]["tok"].astype(compute_dtype)[tokens]
    if cfg.pos_embedding == "learned":
        x = x + params["embed"]["pos"].astype(compute_dtype)[positions]
        cos = sin = jnp.zeros((B, T, cfg.head_dim // 2), jnp.float32)
    else:
        cos, sin = rope_freqs(cfg, positions)
    return x, cos, sin


def scan_layers(layer_params: Params, cfg: ModelConfig, x: jax.Array,
                k: jax.Array, v: jax.Array, positions: jax.Array,
                mask: jax.Array, cos: jax.Array, sin: jax.Array,
                fresh: bool = False,
                k_s: Optional[jax.Array] = None,
                v_s: Optional[jax.Array] = None):
    """lax.scan of transformer_layer over layer-stacked leaves.

    Works on any leading-layer-count slice (full model, or one pipeline
    stage's slice — parallel/pipeline.py scans each stage's local layers
    with this same body). Returns (x, new_k, new_v), plus
    (new_k_s, new_v_s) when scanning an int8 cache (k_s/v_s [L,B,Kv,S]).
    """
    compute_dtype = jnp.dtype(cfg.dtype)
    quant = k_s is not None

    def body(x, scanned):
        lp, *kv = scanned
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        x, *kv = transformer_layer(x, lp, cfg, *kv[:2],
                                   positions, mask, cos, sin, fresh,
                                   *kv[2:])
        return x, tuple(kv)

    xs = (layer_params, k, v, k_s, v_s) if quant else (layer_params, k, v)
    x, out = lax.scan(body, x, xs)
    return (x, *out)


def final_logits(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    """Final norm + LM head. Returns logits [B,T,V] float32."""
    compute_dtype = jnp.dtype(cfg.dtype)
    if cfg.arch == "gpt2":
        x = layer_norm(x, params["final_norm"]["scale"],
                       params["final_norm"]["bias"], cfg.norm_eps)
    else:
        x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)

    if cfg.tie_embeddings:
        logits = jnp.einsum("btd,vd->btv", x,
                            params["embed"]["tok"].astype(compute_dtype))
    else:
        logits = qeinsum("btd,dv->btv", x, params["lm_head"], compute_dtype)
    return logits.astype(jnp.float32)


def decode_attend(q: jax.Array, k_new: jax.Array, v_new: jax.Array,
                  ck: jax.Array, cv: jax.Array, start: jax.Array,
                  cfg: ModelConfig, k_s: Optional[jax.Array] = None,
                  v_s: Optional[jax.Array] = None,
                  wk: Optional[jax.Array] = None,
                  wv: Optional[jax.Array] = None,
                  wk_s: Optional[jax.Array] = None,
                  wv_s: Optional[jax.Array] = None) -> jax.Array:
    """One-token attention over (old cache) + (the token itself).

    The general path writes K/V into the cache BEFORE attending, which
    forces a per-layer scattered cache update inside the layer scan — 2L
    batched-dynamic-slice scatters per decode step, the dominant cost of
    the decode loop at serving batch sizes (measured on v5e). Attending
    over the unmodified cache (positions < start, no write yet) plus an
    explicit self-attention term is mathematically identical for causal
    decode and lets the caller write ALL layers' new K/V in one batched
    update after the scan (see _decode_forward).

    q [B,1,Nq,H]; k_new/v_new [B,1,Kv,H]; ck/cv [B,S,Kv,H]; start [B].
    int8 cache: ck/cv are codes in [B,Kv,S,H] order with scales k_s/v_s
    [B,Kv,S]; only int8 bytes stream from HBM (the convert + scale fuse
    into the dots) and the self term stays full precision.

    Window (write-combining fused decode, engine._generate_fused): wk/wv
    hold the previous not-yet-flushed decoded tokens' K/V for this
    layer, in the cache's REPRESENTATION (int8 codes + scales in quant
    mode), stacked step-major: [W,B,Kv,H] both modes, scales wk_s/wv_s
    [W,B,Kv]. Every entry is LIVE (the unrolled fused loop passes
    exactly the steps decoded so far — see decode_step_win); they sit
    at absolute positions start..start+W-1. `start` is the FLUSHED
    length per row (= tokens actually in ck/cv).
    """
    B, _, Nq, H = q.shape
    quant = k_s is not None
    S = ck.shape[2] if quant else ck.shape[1]
    Kv = k_new.shape[2]
    G = Nq // Kv
    qg = q.reshape(B, Kv, G, H)
    compute = q.dtype
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    k_eq = "bksh" if quant else "bskh"
    s_c = jnp.einsum(f"bkgh,{k_eq}->bkgs", qg, _cast_float(ck, compute),
                     preferred_element_type=jnp.float32)
    if quant:
        s_c = s_c * k_s[:, :, None, :]
    s_c = s_c * scale
    older = jnp.arange(S)[None, :] < start[:, None]          # strictly past
    s_c = jnp.where(older[:, None, None, :], s_c, -1e30)
    parts_s = [s_c]

    if wk is not None:
        s_w = jnp.einsum("bkgh,cbkh->bkgc", qg, _cast_float(wk, compute),
                         preferred_element_type=jnp.float32)
        if quant:
            s_w = s_w * jnp.moveaxis(wk_s, 0, -1)[:, :, None, :]
        s_w = s_w * scale
        parts_s.append(s_w)

    s_self = jnp.sum(qg.astype(jnp.float32) *
                     k_new.reshape(B, Kv, 1, H).astype(jnp.float32),
                     axis=-1, keepdims=True) * scale          # [B,Kv,G,1]
    parts_s.append(s_self)
    s = jnp.concatenate(parts_s, axis=-1)
    p = jax.nn.softmax(s, axis=-1)

    p_c = p[..., :S]
    if quant:
        p_c = p_c * v_s[:, :, None, :]
    out = jnp.einsum(f"bkgs,{k_eq}->bkgh", p_c.astype(compute),
                     _cast_float(cv, compute))
    if wk is not None:
        p_w = p[..., S:-1]
        if quant:
            p_w = p_w * jnp.moveaxis(wv_s, 0, -1)[:, :, None, :]
        out = out + jnp.einsum("bkgc,cbkh->bkgh", p_w.astype(compute),
                               _cast_float(wv, compute))
    out = out + p[..., -1:].astype(v_new.dtype) * v_new.reshape(B, Kv, 1, H)
    return out.reshape(B, 1, Nq, H)


def _decode_layer_body(x, lp, cfg: ModelConfig, cache: KVCache, i,
                       cos, sin, start, wk_i=None, wv_i=None, wks_i=None,
                       wvs_i=None):
    """One decode layer against layer `i`'s slice of the closed-over
    cache (+ optional write-combining window entries for THIS layer:
    wk_i/wv_i [W,B,Kv,H], scales [W,B,Kv] — already layer-sliced by the
    caller). The single layer body shared by _decode_forward and
    decode_step_win so the per-step and windowed decode paths cannot
    drift. Returns (x, k_new, v_new) with k/v [B,1,Kv,H] in compute
    dtype.
    """
    compute_dtype = jnp.dtype(cfg.dtype)
    lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
    ck = lax.dynamic_index_in_dim(cache.k, i, 0, keepdims=False)
    cv = lax.dynamic_index_in_dim(cache.v, i, 0, keepdims=False)
    k_s = v_s = None
    if cache.quantized:
        k_s = lax.dynamic_index_in_dim(cache.k_scale, i, 0, keepdims=False)
        v_s = lax.dynamic_index_in_dim(cache.v_scale, i, 0, keepdims=False)
    h = pre_norm(x, lp["ln1"], cfg)
    q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
    out = decode_attend(q, k, v, ck, cv, start, cfg, k_s, v_s,
                        wk_i, wv_i, wks_i, wvs_i)
    x = x + attn_output(out, lp["attn"], cfg)
    x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
    return x, k, v


def _decode_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                    cache: KVCache, positions: jax.Array
                    ) -> Tuple[jax.Array, KVCache]:
    """Single-token decode step with ONE batched cache write.

    The layer scan attends via decode_attend (old cache + self term) and
    emits each layer's fresh K/V as stacked scan outputs; the cache is
    then updated for every layer at once with a single vmapped
    dynamic-update-slice — O(1) update ops per step instead of O(L).
    """
    B = tokens.shape[0]
    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    start = positions[:, 0]
    quant = cache.quantized

    # The cache is READ-ONLY inside the layer scan (writes are deferred
    # to the one-shot update below), so it is closed over and indexed
    # in-body rather than passed as scan xs: xs slicing materializes a
    # dynamic-slice COPY of every layer's [B,S,Kv,H] slice per step —
    # measured as the single largest op (~45% of decode step time) in
    # the v5e fused-generate trace.
    def layer(carry, lp):
        x, i = carry
        x, k, v = _decode_layer_body(x, lp, cfg, cache, i, cos, sin, start)
        if quant:
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            return (x, i + 1), (kq, vq, ksc, vsc)
        return (x, i + 1), (k.astype(cache.k.dtype),
                            v.astype(cache.v.dtype))

    (x, _), outs = lax.scan(layer, (x, 0), params["layers"])
    logits = final_logits(params, cfg, x)

    if quant:
        # codes: [L,B,Kv,S,H] <- scan outputs [L,B,1,Kv,H] -> [L,B,Kv,1,H]
        def updq(c_b, n_b, s_b):  # [L,Kv,S,H] <- [L,Kv,1,H] at (0,0,s,0)
            return lax.dynamic_update_slice(c_b, n_b, (0, 0, s_b, 0))

        def upd_s(c_b, n_b, s_b):  # [L,Kv,S] <- [L,Kv,1] at (0,0,s)
            return lax.dynamic_update_slice(c_b, n_b, (0, 0, s_b))

        kq, vq, ksc, vsc = outs
        new_k = jax.vmap(updq, in_axes=(1, 1, 0), out_axes=1)(
            cache.k, kq.transpose(0, 1, 3, 2, 4), start)
        new_v = jax.vmap(updq, in_axes=(1, 1, 0), out_axes=1)(
            cache.v, vq.transpose(0, 1, 3, 2, 4), start)
        new_ks = jax.vmap(upd_s, in_axes=(1, 1, 0), out_axes=1)(
            cache.k_scale, ksc.transpose(0, 1, 3, 2), start)
        new_vs = jax.vmap(upd_s, in_axes=(1, 1, 0), out_axes=1)(
            cache.v_scale, vsc.transpose(0, 1, 3, 2), start)
        return logits, KVCache(new_k, new_v, cache.length + 1,
                               new_ks, new_vs)

    def upd(c_b, n_b, s_b):  # [L,S,Kv,H] <- [L,1,Kv,H] at (0, s_b, 0, 0)
        return lax.dynamic_update_slice(c_b, n_b, (0, s_b, 0, 0))

    ks, vs = outs
    new_k = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.k, ks, start)
    new_v = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.v, vs, start)
    return logits, KVCache(new_k, new_v, cache.length + 1)


# ---------------------------------------------------------------------------
# Write-combined decode window (engine fused generate)
#
# Every in-loop update of the big cache costs a copy of the whole pool on
# TPU (XLA does not alias scatters into while-loop carries here; measured
# ~2.4 ms/step at the 1B/batch-128 operating point — the largest single
# term of the decode step). The fused generate therefore decodes C tokens
# per outer scan iteration and flushes all C into the big cache with ONE
# ragged write per C steps, amortizing the copy. The C steps are UNROLLED
# inside the iteration, so the not-yet-flushed "window" needs no device
# buffer at all: each step's K/V is an SSA value held in a Python list
# (r4 had a [.., C, ..] window buffer updated per step with
# dynamic-update-slice; XLA's layout assignment made every insert a
# strided scatter of H-byte segments at 15 GiB/s — 19% of the decode step
# on v5e, docs/decode_profile_r5.md — and reassigned any step-major
# layout right back). The window uses the cache's representation (int8
# codes + scales in quant mode), so attention numerics are bit-identical
# to the step-by-step path.
# ---------------------------------------------------------------------------

def decode_step_win(params: Params, cfg: ModelConfig, tokens: jax.Array,
                    cache: KVCache, prev: list, wstep: int):
    """One decode step against (cache + prior window steps + self).

    tokens [B,1]; the token sits at absolute position cache.length +
    wstep (cache.length = flushed tokens; `prev` holds steps
    0..wstep-1 of the current flush group as a list of new_kv tuples —
    exactly what this function returned for them). No cache writes.
    Returns (logits, new_kv): the per-layer stacked K/V of this token —
    fp (ks [L,B,Kv,H], vs) / quant (kq, vq, ks_scale [L,B,Kv], vs_scale).

    The prior steps are stacked ONCE per step into [L,W,...] arrays and
    ride into the layer scan as `xs` leaves, so each layer's xs slice is
    one CONTIGUOUS [W,B,Kv,H] window operand for decode_attend (stacking
    inside the layer body instead costs ~2x the step's window traffic in
    128KB strided slices + concats — measured on v5e, r5 profile).
    """
    quant = cache.quantized
    positions = (cache.length + wstep)[:, None]
    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    start = cache.length
    win = ()
    if prev:  # [L,W,B,Kv,H] codes (+ [L,W,B,Kv] scales in quant mode)
        win = tuple(jnp.stack(c, axis=1) for c in zip(*prev))

    def layer(carry, scanned):
        x, i = carry
        lp, w = scanned  # w: per-layer [W,B,Kv,H] (+ [W,B,Kv]) or ()
        wk_i, wv_i, *wsc = w if w else (None, None)
        wks_i, wvs_i = wsc if wsc else (None, None)
        x, k, v = _decode_layer_body(x, lp, cfg, cache, i, cos, sin, start,
                                     wk_i, wv_i, wks_i, wvs_i)
        if quant:
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            return (x, i + 1), (kq[:, 0], vq[:, 0], ksc[:, 0], vsc[:, 0])
        return (x, i + 1), (k[:, 0].astype(cache.k.dtype),
                            v[:, 0].astype(cache.v.dtype))

    (x, _), new_kv = lax.scan(layer, (x, 0), (params["layers"], win))
    return final_logits(params, cfg, x), new_kv


def flush_window(cache: KVCache, steps: list,
                 uniform: bool = False) -> KVCache:
    """Write a whole flush group (C tokens per row, `steps` = the list of
    decode_step_win new_kv tuples) into the big cache at each row's
    flushed length — the one ragged write per C steps. The stack into
    cache dim order is a copy of the small window only, amortized over
    C steps.

    `uniform` (static) asserts every row's flushed length is equal (all
    prompts the same length — the batch-benchmark shape). The update is
    then ONE dynamic_update_slice at a scalar offset, which XLA aliases
    with the scan carry and performs in place; the general ragged path
    (vmapped per-row updates) rolls into a loop whose first update
    COPIES each pool — ~1.8 ms per pool per flush at the 1B/batch-128
    operating point (docs/decode_profile_r5.md)."""
    start = cache.length
    C = len(steps)
    if cache.quantized:
        kq = jnp.stack([s[0] for s in steps], axis=3)   # [L,B,Kv,C,H]
        vq = jnp.stack([s[1] for s in steps], axis=3)
        ksc = jnp.stack([s[2] for s in steps], axis=3)  # [L,B,Kv,C]
        vsc = jnp.stack([s[3] for s in steps], axis=3)
        if uniform:
            s0 = start[0]
            new_k = lax.dynamic_update_slice(cache.k, kq, (0, 0, 0, s0, 0))
            new_v = lax.dynamic_update_slice(cache.v, vq, (0, 0, 0, s0, 0))
            new_ks = lax.dynamic_update_slice(cache.k_scale, ksc,
                                              (0, 0, 0, s0))
            new_vs = lax.dynamic_update_slice(cache.v_scale, vsc,
                                              (0, 0, 0, s0))
            return KVCache(new_k, new_v, cache.length + C, new_ks, new_vs)

        def updq(c_b, n_b, s_b):  # [L,Kv,S,H] <- [L,Kv,C,H] at (0,0,s,0)
            return lax.dynamic_update_slice(c_b, n_b, (0, 0, s_b, 0))

        def upd_s(c_b, n_b, s_b):  # [L,Kv,S] <- [L,Kv,C] at (0,0,s)
            return lax.dynamic_update_slice(c_b, n_b, (0, 0, s_b))

        new_k = jax.vmap(updq, in_axes=(1, 1, 0), out_axes=1)(
            cache.k, kq, start)
        new_v = jax.vmap(updq, in_axes=(1, 1, 0), out_axes=1)(
            cache.v, vq, start)
        new_ks = jax.vmap(upd_s, in_axes=(1, 1, 0), out_axes=1)(
            cache.k_scale, ksc, start)
        new_vs = jax.vmap(upd_s, in_axes=(1, 1, 0), out_axes=1)(
            cache.v_scale, vsc, start)
        return KVCache(new_k, new_v, cache.length + C, new_ks, new_vs)

    ks = jnp.stack([s[0] for s in steps], axis=2)       # [L,B,C,Kv,H]
    vs = jnp.stack([s[1] for s in steps], axis=2)
    if uniform:
        s0 = start[0]
        new_k = lax.dynamic_update_slice(cache.k, ks, (0, 0, s0, 0, 0))
        new_v = lax.dynamic_update_slice(cache.v, vs, (0, 0, s0, 0, 0))
        return KVCache(new_k, new_v, cache.length + C)

    def upd(c_b, n_b, s_b):  # [L,S,Kv,H] <- [L,C,Kv,H] at (0,s,0,0)
        return lax.dynamic_update_slice(c_b, n_b, (0, s_b, 0, 0))

    new_k = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.k, ks, start)
    new_v = jax.vmap(upd, in_axes=(1, 1, 0), out_axes=1)(cache.v, vs, start)
    return KVCache(new_k, new_v, cache.length + C)


def _fresh_prefill_forward(params: Params, cfg: ModelConfig,
                           tokens: jax.Array, cache: KVCache, positions,
                           last_index) -> Tuple[jax.Array, KVCache]:
    """Fresh-prefill fast path: the cache stays OUT of the layer scan.

    A fresh prefill (positions 0..T-1, nothing live in the cache) never
    READS the cache — attention is over the freshly-projected K/V (flash
    kernel, or a dense causal fallback over the same bf16 values). So
    the pools ride the scan CARRY and each layer writes its (already
    cache-representation) K/V with one dynamic_update_slice at the
    layer index — XLA's canonical in-place carry update. The general
    path instead threads pools as scan xs/ys: the xs slicing copies a
    layer slice per step and the stacked ys make a SECOND full pool —
    2x pool HBM, the term that pushed 8B/batch-128 prefill over a v5e
    chip's 16 GiB.

    Padded rows: like the general path, pad positions' K/V land in the
    cache; they sit at slots >= true_len that no causal query reaches
    until decode overwrites them (engine/engine.py padding contract).
    """
    B, T = tokens.shape
    quant = cache.quantized
    compute_dtype = jnp.dtype(cfg.dtype)
    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, T)  # causal over the chunk itself

    def body(carry, lp):
        x, pools, i = carry
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        # no-cache layer body: same recipe as every other path, with the
        # raw projected K/V returned for the pool write below
        x, k, v = transformer_layer(x, lp, cfg, None, None, positions,
                                    mask, cos, sin, fresh=True)
        ck, cv, cks, cvs = pools
        if quant:
            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            ck = lax.dynamic_update_slice(
                ck, kq.transpose(0, 2, 1, 3)[None], (i, 0, 0, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, vq.transpose(0, 2, 1, 3)[None], (i, 0, 0, 0, 0))
            cks = lax.dynamic_update_slice(
                cks, ks.transpose(0, 2, 1)[None], (i, 0, 0, 0))
            cvs = lax.dynamic_update_slice(
                cvs, vs.transpose(0, 2, 1)[None], (i, 0, 0, 0))
        else:
            ck = lax.dynamic_update_slice(
                ck, k.astype(ck.dtype)[None], (i, 0, 0, 0, 0))
            cv = lax.dynamic_update_slice(
                cv, v.astype(cv.dtype)[None], (i, 0, 0, 0, 0))
        return (x, (ck, cv, cks, cvs), i + 1), None

    pools0 = (cache.k, cache.v, cache.k_scale, cache.v_scale)
    (x, pools, _), _ = lax.scan(body, (x, pools0, 0), params["layers"])
    if last_index is not None:
        x = jnp.take_along_axis(
            x, last_index[:, None, None].astype(jnp.int32), axis=1)
    logits = final_logits(params, cfg, x)
    new_len = cache.length + T
    return logits, KVCache(pools[0], pools[1], new_len, pools[2], pools[3])


def forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
            cache: KVCache, positions: Optional[jax.Array] = None,
            fresh: bool = False,
            last_index: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, KVCache]:
    """Run the model over `tokens` [B,T], reading/updating `cache`.

    positions defaults to cache.length[:,None] + arange(T) (append).
    `fresh` (static) = no LIVE entries precede this call's tokens and
    positions start at 0 (recycled buffers may hold stale bytes —
    masking, not zeroing, is the correctness mechanism); only
    then may the flash prefill kernel be used (see attention_block).
    Single-token warm calls take the decode fast path (_decode_forward:
    deferred one-shot cache write). Returns (logits [B,T,V] float32,
    updated cache).

    last_index [B]: when given, the LM head runs ONLY on each row's
    hidden state at that (row-relative) index — logits come back
    [B,1,V]. Prefill needs just the last real token's logits, and the
    full-T head is the single largest prefill term at LLM vocab sizes
    (8B/V=128k at B=T=128: an 8.4 GB f32 [B,T,V] buffer plus 6% of the
    prefill FLOPs).
    """
    B, T = tokens.shape
    if positions is None:
        positions = cache.length[:, None] + jnp.arange(T)[None, :]
    if T == 1 and not fresh:
        return _decode_forward(params, cfg, tokens, cache, positions)
    if fresh and T > 1:
        return _fresh_prefill_forward(params, cfg, tokens, cache,
                                      positions, last_index)

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)
    x, *new_kv = scan_layers(params["layers"], cfg, x, cache.k, cache.v,
                             positions, mask, cos, sin, fresh,
                             cache.k_scale, cache.v_scale)
    if last_index is not None:
        x = jnp.take_along_axis(
            x, last_index[:, None, None].astype(jnp.int32), axis=1)
    logits = final_logits(params, cfg, x)
    new_len = cache.length + T
    return logits, KVCache(*new_kv[:2], new_len, *new_kv[2:])


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random init (normal, 0.02 std — GPT-2 style) in cfg.param_dtype."""
    pdt = jnp.dtype(cfg.param_dtype)
    L, D, Nq, Kv, H, F, V = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                             cfg.num_kv_heads, cfg.head_dim,
                             cfg.intermediate_size, cfg.vocab_size)
    keys = iter(jax.random.split(key, 32))

    def w(k, *shape, std=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * std).astype(pdt)

    layers: Params = {
        "ln1": {"scale": jnp.ones((L, D), pdt)},
        "ln2": {"scale": jnp.ones((L, D), pdt)},
        "attn": {
            "wq": w(next(keys), L, D, Nq, H),
            "wk": w(next(keys), L, D, Kv, H),
            "wv": w(next(keys), L, D, Kv, H),
            "wo": w(next(keys), L, Nq, H, D),
        },
    }
    if cfg.use_bias:
        layers["ln1"]["bias"] = jnp.zeros((L, D), pdt)
        layers["ln2"]["bias"] = jnp.zeros((L, D), pdt)
        layers["attn"].update(
            bq=jnp.zeros((L, Nq, H), pdt), bk=jnp.zeros((L, Kv, H), pdt),
            bv=jnp.zeros((L, Kv, H), pdt), bo=jnp.zeros((L, D), pdt),
        )
    if cfg.is_moe:
        E = cfg.num_experts
        layers["moe"] = {
            "router": w(next(keys), L, D, E),
            "w_gate": w(next(keys), L, E, D, F),
            "w_up": w(next(keys), L, E, D, F),
            "w_down": w(next(keys), L, E, F, D),
        }
    elif cfg.arch == "gpt2":
        layers["mlp"] = {
            "w_up": w(next(keys), L, D, F), "b_up": jnp.zeros((L, F), pdt),
            "w_down": w(next(keys), L, F, D), "b_down": jnp.zeros((L, D), pdt),
        }
    else:
        layers["mlp"] = {
            "w_gate": w(next(keys), L, D, F),
            "w_up": w(next(keys), L, D, F),
            "w_down": w(next(keys), L, F, D),
        }

    params: Params = {
        "embed": {"tok": w(next(keys), V, D)},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((D,), pdt)},
    }
    if cfg.pos_embedding == "learned":
        params["embed"]["pos"] = w(next(keys), cfg.max_seq_len, D)
    if cfg.arch == "gpt2":
        params["final_norm"]["bias"] = jnp.zeros((D,), pdt)
    if not cfg.tie_embeddings:
        params["lm_head"] = w(next(keys), D, V)
    return params


@dataclasses.dataclass(frozen=True)
class Model:
    """Thin handle bundling a config with the functional API."""

    cfg: ModelConfig

    def init(self, key: jax.Array) -> Params:
        return init_params(self.cfg, key)

    def init_cache(self, batch: int, max_seq: int, dtype=None) -> KVCache:
        return init_cache(self.cfg, batch, max_seq, dtype)

    def __call__(self, params: Params, tokens: jax.Array, cache: KVCache,
                 positions: Optional[jax.Array] = None):
        return forward(params, self.cfg, tokens, cache, positions)
