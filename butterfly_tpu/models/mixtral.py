"""Mixtral-8x7B MoE family (BASELINE.json configs[3], expert-parallel).

Llama-style attention (GQA + RoPE) with a top-2-of-8 expert SwiGLU FFN;
expressed via ModelConfig over models/common.py, with the expert-parallel
dispatch in parallel/expert.py (cfg.moe_impl="ep").
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from butterfly_tpu.core.config import ModelConfig, mixtral_8x7b  # noqa: F401
from butterfly_tpu.models.common import Model


def model(cfg: ModelConfig | None = None) -> Model:
    return Model(cfg or mixtral_8x7b())


def params_from_hf_state_dict(sd: Dict[str, Any], cfg: ModelConfig) -> Dict:
    """Convert HF MixtralForCausalLM weights to our pytree.

    HF expert weights live at
    model.layers.{l}.block_sparse_moe.experts.{e}.w1|w2|w3.weight with
    w1=gate [F,D], w2=down [D,F], w3=up [F,D]; the router is
    block_sparse_moe.gate.weight [E,D]. Our layout stacks layers AND
    experts: w_gate/w_up [L,E,D,F], w_down [L,E,F,D], router [L,D,E].
    """
    def g(name):
        t = sd[name]
        return np.asarray(
            t.detach().cpu().numpy() if hasattr(t, "detach") else t,
            dtype=np.float32)

    L, D = cfg.num_layers, cfg.hidden_size
    Nq, Kv, H, E = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_experts

    def stack(fmt, post=lambda a: a):
        return jnp.asarray(np.stack([post(g(fmt.format(i)))
                                     for i in range(L)]))

    def proj(n_heads):
        return lambda a: a.T.reshape(D, n_heads, H)

    def experts(which):  # w1|w2|w3 -> [L,E,...] transposed to [in,out]
        return jnp.asarray(np.stack([
            np.stack([g(f"model.layers.{l}.block_sparse_moe.experts."
                        f"{e}.{which}.weight").T for e in range(E)])
            for l in range(L)]))

    params = {
        "embed": {"tok": jnp.asarray(g("model.embed_tokens.weight"))},
        "layers": {
            "ln1": {"scale": stack("model.layers.{}.input_layernorm.weight")},
            "ln2": {"scale": stack(
                "model.layers.{}.post_attention_layernorm.weight")},
            "attn": {
                "wq": stack("model.layers.{}.self_attn.q_proj.weight",
                            proj(Nq)),
                "wk": stack("model.layers.{}.self_attn.k_proj.weight",
                            proj(Kv)),
                "wv": stack("model.layers.{}.self_attn.v_proj.weight",
                            proj(Kv)),
                "wo": stack("model.layers.{}.self_attn.o_proj.weight",
                            post=lambda a: a.T.reshape(Nq, H, D)),
            },
            "moe": {
                "router": stack(
                    "model.layers.{}.block_sparse_moe.gate.weight",
                    post=lambda a: a.T),              # [D,E]
                "w_gate": experts("w1"),
                "w_up": experts("w3"),
                "w_down": experts("w2"),
            },
        },
        "final_norm": {"scale": jnp.asarray(g("model.norm.weight"))},
    }
    if "lm_head.weight" in sd:
        params["lm_head"] = jnp.asarray(g("lm_head.weight").T)
    else:
        params["lm_head"] = jnp.asarray(g("model.embed_tokens.weight").T)
    return params
