"""In-process fleet topologies: N prefill + M decode replicas behind
one disaggregated control plane, all in this process.

The local twin of a real deployment (`butterfly serve --role ...` x N
behind `butterfly route --disaggregate`): each replica is a full
Scheduler + ServingEngine + HTTP front on a loopback port, the control
plane is the real ControlPlaneState/FleetHandler — only the network is
loopback. Used by `butterfly fleet --topology 2p2d` (manual
debugging), tests/test_fleet.py (the soak), and the fleet benchmark
(obs/benchmark.py). All replicas share ONE param tree (same weights,
as a real fleet would load from one checkpoint), which is also what
makes cross-replica KV bytes interchangeable.

``ReplicaHandle.restart()`` bounces the replica's HTTP front (the
listener drops mid-fleet and comes back on the same port) — the
rolling-restart half of the soak's drain/restart cycle; the drain half
goes through the control plane's inherited /router/drain admin
surface.
"""
from __future__ import annotations

import re
import threading
import time
from http.server import ThreadingHTTPServer
from typing import List, Optional, Tuple

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.fleet.controlplane import (
    ControlPlaneState, make_fleet_handler)
from butterfly_tpu.obs.registry import MetricsRegistry
from butterfly_tpu.router.policy import PrefixAffinityPolicy
from butterfly_tpu.router.pool import ReplicaPool


def parse_topology(spec: str) -> List[str]:
    """Topology spec -> per-replica role list. Arbitrary 'NpMd' shapes
    ('2p2d', '3p5d', '0p4d' — a zero side means that tier starts empty,
    the elastic-fleet starting shapes; '0p0d' is meaningless) plus the
    bare-digit shorthand '4' for a role-less 4x'both' pool."""
    m = re.fullmatch(r"(\d+)p(\d+)d", spec.strip().lower())
    if m:
        n_pre, n_dec = int(m.group(1)), int(m.group(2))
        if n_pre + n_dec < 1:
            raise ValueError(f"topology {spec!r} needs >=1 replica")
        return ["prefill"] * n_pre + ["decode"] * n_dec
    if spec.strip().isdigit() and int(spec) >= 1:
        return ["both"] * int(spec)  # role-less pool
    raise ValueError(f"unparseable topology {spec!r} (want e.g. '2p2d')")


class ReplicaHandle:
    def __init__(self, state, httpd, sched, role: str, host: str,
                 handler_cls=None):
        self.state = state
        self.httpd = httpd
        self.sched = sched
        self.role = role
        self.host = host
        self.port = httpd.server_port
        self.rid = f"{host}:{self.port}"
        self.url = f"http://{self.rid}"
        # the handler class the front was built with (incl. any chaos
        # wrapper) so a restart keeps injecting the same fault plan
        self.handler_cls = handler_cls

    def restart(self) -> None:
        """Bounce the HTTP front on the same port (connects fail for
        the gap, exactly like a rolling binary restart of the serving
        tier; scheduler + KV state survive, as they would behind a
        real graceful-restart supervisor)."""
        from butterfly_tpu.serve.server import make_handler
        handler = self.handler_cls or make_handler(self.state)
        self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd = ThreadingHTTPServer((self.host, self.port), handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.state.stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


class FleetHandle:
    def __init__(self, replicas: List[ReplicaHandle], cp_state, cp_httpd,
                 spawn_ctx: Optional[dict] = None):
        self.replicas = replicas
        self.state = cp_state
        self.httpd = cp_httpd
        self.url = f"http://127.0.0.1:{cp_httpd.server_port}"
        self.by_rid = {r.rid: r for r in replicas}
        # runtime spawn context (model + shared param tree + replica
        # kwargs) captured by start_fleet: what makes a spawned
        # replica's KV bytes interchangeable with the incumbents'
        self._spawn_ctx = spawn_ctx
        self._lock = threading.Lock()
        self._tier_index: dict = {}
        for r in replicas:
            self._tier_index[r.role] = self._tier_index.get(r.role, 0) + 1

    @property
    def rids(self) -> List[str]:
        return [r.rid for r in self.replicas]

    def spawn(self, role: str) -> ReplicaHandle:
        """Grow one tier at runtime: start a replica on the SHARED
        param tree, warm it (start_replica warms BEFORE its HTTP front
        binds — warm-before-join is structural, a joining replica can
        never serve a compile-cold request), then attach it to the
        pool, probe it so its role is known before anything routes,
        and remap the affinity ring."""
        if self._spawn_ctx is None:
            raise RuntimeError("this fleet was started without a spawn "
                               "context (start_fleet builds one)")
        with self._lock:
            idx = self._tier_index.get(role, 0)
            self._tier_index[role] = idx + 1
        ctx = self._spawn_ctx
        handle = start_replica(ctx["model"], ctx["params"], role,
                               chaos_index=idx, **ctx["replica_kw"])
        pool = self.state.pool
        pool.add(handle.rid)
        rep = pool.get(handle.rid)
        if rep is not None:
            pool.probe_one(rep)  # learn role/load before routing
        self.state.policy.rebuild_ring()
        with self._lock:
            self.replicas.append(handle)
            self.by_rid[handle.rid] = handle
        return handle

    def retire(self, rid: str, timeout: float = 30.0) -> bool:
        """Shrink a tier at runtime, drain-before-retire: mark the
        member draining (no NEW requests route to it), wait for its
        proxied legs AND its own queue/runners to empty, then stop its
        front, detach it from the pool, and remap the affinity ring.
        On timeout the replica is retired anyway — bounded shrink beats
        a wedged runner pinning capacity forever. False if unknown."""
        handle = self.by_rid.get(rid)
        if handle is None:
            return False
        pool = self.state.pool
        if len(pool.replicas) <= 1:
            raise ValueError("cannot retire the last replica")
        pool.set_drain(rid, True)
        deadline = time.monotonic() + timeout
        sched = handle.sched
        while time.monotonic() < deadline:
            rep = pool.get(rid)
            outstanding = rep.outstanding if rep is not None else 0
            # cross-thread reads of the scheduler's queues are racy but
            # monotone-enough for a drain check: a request in flight is
            # visible in at least one of these until its finish callback
            if outstanding == 0 and not sched.waiting \
                    and not sched.running and not sched._prefill_group:
                break
            time.sleep(0.02)
        handle.stop()
        pool.remove(rid)
        self.state.policy.rebuild_ring()
        with self._lock:
            self.replicas.remove(handle)
            self.by_rid.pop(rid, None)
        return True

    def stop(self) -> None:
        self.state.pool.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        for r in self.replicas:
            r.stop()


def start_replica(model, params, role: str, *, page_size: int = 8,
                  max_batch: int = 2, max_seq: int = 128,
                  num_pages: Optional[int] = None,
                  host: str = "127.0.0.1", warm: bool = True,
                  warm_len: Optional[int] = None,
                  slo_ttft_s: Optional[float] = None,
                  slo_itl_s: Optional[float] = None,
                  host_kv_tier_mb: float = 0.0,
                  host_kv_tier_dir: Optional[str] = None,
                  chaos=None, chaos_index: int = 0) -> ReplicaHandle:
    """One in-process serve replica on a fresh loopback port. Prefix
    caching is always on — it is the registry KV transfer addresses
    pages through. Tracing is always on — the fleet trace merge
    (GET /fleet/trace) joins each replica's /debug/requests timeline
    into the cross-replica waterfall, exactly like a real `butterfly
    serve` replica (which traces by default). Warming runs BEFORE the
    scheduler loop thread starts (one thread ticks a scheduler, ever).
    `chaos` (fleet/chaos.py ChaosPlan) wraps the HTTP handler in the
    seeded fault-injection hook; `chaos_index` is this replica's index
    within its role tier (plans target e.g. 'decode:0')."""
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.obs.trace import Tracer
    from butterfly_tpu.sched.scheduler import Scheduler
    from butterfly_tpu.serve.server import ServerState, make_handler
    from butterfly_tpu.utils.tokenizer import ByteTokenizer

    from butterfly_tpu.obs.ticklog import FlightRecorder

    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=page_size, num_pages=num_pages,
                       prefix_caching=True,
                       host_kv_tier_mb=host_kv_tier_mb,
                       host_kv_tier_dir=host_kv_tier_dir)
    # flight recorder always on, like tracing: the fleet rollup
    # (GET /fleet/flightrecorder) merges every replica's ring
    sched = Scheduler(ServingEngine(model, params, rt), tracer=Tracer(),
                      slo_ttft_s=slo_ttft_s, slo_itl_s=slo_itl_s,
                      flightrec=FlightRecorder())
    if warm:
        # compile prefill + decode off any measured clock, BOTH prefill
        # flavors: the first warm prompt runs the fresh program, the
        # repeat prefix-hits its registered pages and compiles the
        # warm-continuation program the transfer handoff's tail prefill
        # uses. warm_len should match the expected workload's prefill
        # bucket (bucket_len) or the first measured request pays XLA.
        wl = min(warm_len or page_size * 2, max_seq - 4)
        for _ in range(2):
            w = sched.submit([1] * wl, max_new_tokens=2)
            sched.run_until_done()
            assert w.done
    state = ServerState(sched, ByteTokenizer(), role=role)
    state.thread.start()
    handler_cls = make_handler(state)
    ident = None
    if chaos is not None:
        from butterfly_tpu.fleet.chaos import ChaosIdent, make_chaos_handler
        ident = ChaosIdent(role=role, index=chaos_index)
        handler_cls = make_chaos_handler(handler_cls, chaos, ident)
    httpd = ThreadingHTTPServer((host, 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    handle = ReplicaHandle(state, httpd, sched, role, host,
                           handler_cls=handler_cls)
    if ident is not None:
        ident.rid = handle.rid  # known only after the port binds
    return handle


def start_fleet(topology: str = "2p2d", *, page_size: int = 8,
                max_batch: int = 2, max_seq: int = 128,
                num_pages: Optional[int] = None,
                disagg_threshold: int = 16, affinity_blocks: int = 4,
                probe_interval: float = 0.2, model=None, params=None,
                warm: bool = True,
                warm_len: Optional[int] = None,
                slo_ttft_s: Optional[float] = None,
                slo_itl_s: Optional[float] = None,
                host_kv_tier_mb: float = 0.0,
                host_kv_tier_dir: Optional[str] = None,
                chaos=None) -> FleetHandle:
    """Spin the whole topology: replicas (one shared tiny-model param
    tree unless the caller provides model+params) + control plane, and
    optionally warm every replica's serving programs so the first
    measured request doesn't pay the XLA compile. `chaos` (a
    fleet/chaos.py ChaosPlan) installs the seeded fault hooks on every
    replica front AND the control plane's handoff legs."""
    import jax
    from butterfly_tpu.models.common import Model

    roles = parse_topology(topology)
    if model is None:
        model = Model(tiny("llama", dtype="float32", param_dtype="float32"))
        # btf: disable=BTF006 replicas must share one identical param tree (KV bytes interchangeable)
        params = model.init(jax.random.PRNGKey(0))
    replica_kw = dict(page_size=page_size, max_batch=max_batch,
                      max_seq=max_seq, num_pages=num_pages, warm=warm,
                      warm_len=warm_len, slo_ttft_s=slo_ttft_s,
                      slo_itl_s=slo_itl_s,
                      host_kv_tier_mb=host_kv_tier_mb,
                      host_kv_tier_dir=host_kv_tier_dir, chaos=chaos)
    tier_index: dict = {}
    replicas = []
    for role in roles:
        idx = tier_index.get(role, 0)
        tier_index[role] = idx + 1
        replicas.append(start_replica(
            model, params, role, chaos_index=idx, **replica_kw))
    registry = MetricsRegistry()
    pool = ReplicaPool([r.rid for r in replicas],
                       probe_interval=probe_interval, registry=registry,
                       scrape_metrics=True)
    policy = PrefixAffinityPolicy(pool, page_size=page_size,
                                  affinity_blocks=affinity_blocks)
    cp_state = ControlPlaneState(pool, policy, registry=registry,
                                 read_timeout=120.0,
                                 disagg_threshold=disagg_threshold,
                                 slo_ttft_s=slo_ttft_s,
                                 slo_itl_s=slo_itl_s,
                                 chaos=chaos)
    pool.probe_all()  # learn roles before the first request routes
    pool.start()
    cp_httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                   make_fleet_handler(cp_state))
    threading.Thread(target=cp_httpd.serve_forever, daemon=True).start()
    spawn_ctx = {"model": model, "params": params, "replica_kw": replica_kw}
    return FleetHandle(replicas, cp_state, cp_httpd, spawn_ctx=spawn_ctx)
