"""In-process fleet topologies: N prefill + M decode replicas behind
one disaggregated control plane, all in this process.

The local twin of a real deployment (`butterfly serve --role ...` x N
behind `butterfly route --disaggregate`): each replica is a full
Scheduler + ServingEngine + HTTP front on a loopback port, the control
plane is the real ControlPlaneState/FleetHandler — only the network is
loopback. Used by `butterfly fleet --topology 2p2d` (manual
debugging), tests/test_fleet.py (the soak), and the fleet benchmark
(obs/benchmark.py). All replicas share ONE param tree (same weights,
as a real fleet would load from one checkpoint), which is also what
makes cross-replica KV bytes interchangeable.

``ReplicaHandle.restart()`` bounces the replica's HTTP front (the
listener drops mid-fleet and comes back on the same port) — the
rolling-restart half of the soak's drain/restart cycle; the drain half
goes through the control plane's inherited /router/drain admin
surface.
"""
from __future__ import annotations

import re
import threading
from http.server import ThreadingHTTPServer
from typing import List, Optional, Tuple

from butterfly_tpu.core.config import RuntimeConfig, tiny
from butterfly_tpu.fleet.controlplane import (
    ControlPlaneState, make_fleet_handler)
from butterfly_tpu.obs.registry import MetricsRegistry
from butterfly_tpu.router.policy import PrefixAffinityPolicy
from butterfly_tpu.router.pool import ReplicaPool


def parse_topology(spec: str) -> Tuple[int, int]:
    """'2p2d' -> (2 prefill, 2 decode); '1p1d', '3p1d', ... Also
    accepts '4' as shorthand for a role-less 4x'both' pool (0p0d would
    be meaningless)."""
    m = re.fullmatch(r"(\d+)p(\d+)d", spec.strip().lower())
    if m:
        n_pre, n_dec = int(m.group(1)), int(m.group(2))
        if n_pre < 1 or n_dec < 1:
            raise ValueError(f"topology {spec!r} needs >=1 replica per tier")
        return n_pre, n_dec
    if spec.strip().isdigit() and int(spec) >= 1:
        return 0, int(spec)  # all-'both' pool
    raise ValueError(f"unparseable topology {spec!r} (want e.g. '2p2d')")


class ReplicaHandle:
    def __init__(self, state, httpd, sched, role: str, host: str,
                 handler_cls=None):
        self.state = state
        self.httpd = httpd
        self.sched = sched
        self.role = role
        self.host = host
        self.port = httpd.server_port
        self.rid = f"{host}:{self.port}"
        self.url = f"http://{self.rid}"
        # the handler class the front was built with (incl. any chaos
        # wrapper) so a restart keeps injecting the same fault plan
        self.handler_cls = handler_cls

    def restart(self) -> None:
        """Bounce the HTTP front on the same port (connects fail for
        the gap, exactly like a rolling binary restart of the serving
        tier; scheduler + KV state survive, as they would behind a
        real graceful-restart supervisor)."""
        from butterfly_tpu.serve.server import make_handler
        handler = self.handler_cls or make_handler(self.state)
        self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd = ThreadingHTTPServer((self.host, self.port), handler)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.state.stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


class FleetHandle:
    def __init__(self, replicas: List[ReplicaHandle], cp_state, cp_httpd):
        self.replicas = replicas
        self.state = cp_state
        self.httpd = cp_httpd
        self.url = f"http://127.0.0.1:{cp_httpd.server_port}"
        self.by_rid = {r.rid: r for r in replicas}

    @property
    def rids(self) -> List[str]:
        return [r.rid for r in self.replicas]

    def stop(self) -> None:
        self.state.pool.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        for r in self.replicas:
            r.stop()


def start_replica(model, params, role: str, *, page_size: int = 8,
                  max_batch: int = 2, max_seq: int = 128,
                  num_pages: Optional[int] = None,
                  host: str = "127.0.0.1", warm: bool = True,
                  warm_len: Optional[int] = None,
                  slo_ttft_s: Optional[float] = None,
                  slo_itl_s: Optional[float] = None,
                  chaos=None, chaos_index: int = 0) -> ReplicaHandle:
    """One in-process serve replica on a fresh loopback port. Prefix
    caching is always on — it is the registry KV transfer addresses
    pages through. Tracing is always on — the fleet trace merge
    (GET /fleet/trace) joins each replica's /debug/requests timeline
    into the cross-replica waterfall, exactly like a real `butterfly
    serve` replica (which traces by default). Warming runs BEFORE the
    scheduler loop thread starts (one thread ticks a scheduler, ever).
    `chaos` (fleet/chaos.py ChaosPlan) wraps the HTTP handler in the
    seeded fault-injection hook; `chaos_index` is this replica's index
    within its role tier (plans target e.g. 'decode:0')."""
    from butterfly_tpu.engine.serving import ServingEngine
    from butterfly_tpu.obs.trace import Tracer
    from butterfly_tpu.sched.scheduler import Scheduler
    from butterfly_tpu.serve.server import ServerState, make_handler
    from butterfly_tpu.utils.tokenizer import ByteTokenizer

    from butterfly_tpu.obs.ticklog import FlightRecorder

    rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                       page_size=page_size, num_pages=num_pages,
                       prefix_caching=True)
    # flight recorder always on, like tracing: the fleet rollup
    # (GET /fleet/flightrecorder) merges every replica's ring
    sched = Scheduler(ServingEngine(model, params, rt), tracer=Tracer(),
                      slo_ttft_s=slo_ttft_s, slo_itl_s=slo_itl_s,
                      flightrec=FlightRecorder())
    if warm:
        # compile prefill + decode off any measured clock, BOTH prefill
        # flavors: the first warm prompt runs the fresh program, the
        # repeat prefix-hits its registered pages and compiles the
        # warm-continuation program the transfer handoff's tail prefill
        # uses. warm_len should match the expected workload's prefill
        # bucket (bucket_len) or the first measured request pays XLA.
        wl = min(warm_len or page_size * 2, max_seq - 4)
        for _ in range(2):
            w = sched.submit([1] * wl, max_new_tokens=2)
            sched.run_until_done()
            assert w.done
    state = ServerState(sched, ByteTokenizer(), role=role)
    state.thread.start()
    handler_cls = make_handler(state)
    ident = None
    if chaos is not None:
        from butterfly_tpu.fleet.chaos import ChaosIdent, make_chaos_handler
        ident = ChaosIdent(role=role, index=chaos_index)
        handler_cls = make_chaos_handler(handler_cls, chaos, ident)
    httpd = ThreadingHTTPServer((host, 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    handle = ReplicaHandle(state, httpd, sched, role, host,
                           handler_cls=handler_cls)
    if ident is not None:
        ident.rid = handle.rid  # known only after the port binds
    return handle


def start_fleet(topology: str = "2p2d", *, page_size: int = 8,
                max_batch: int = 2, max_seq: int = 128,
                num_pages: Optional[int] = None,
                disagg_threshold: int = 16, affinity_blocks: int = 4,
                probe_interval: float = 0.2, model=None, params=None,
                warm: bool = True,
                warm_len: Optional[int] = None,
                slo_ttft_s: Optional[float] = None,
                slo_itl_s: Optional[float] = None,
                chaos=None) -> FleetHandle:
    """Spin the whole topology: replicas (one shared tiny-model param
    tree unless the caller provides model+params) + control plane, and
    optionally warm every replica's serving programs so the first
    measured request doesn't pay the XLA compile. `chaos` (a
    fleet/chaos.py ChaosPlan) installs the seeded fault hooks on every
    replica front AND the control plane's handoff legs."""
    import jax
    from butterfly_tpu.models.common import Model

    n_pre, n_dec = parse_topology(topology)
    if model is None:
        model = Model(tiny("llama", dtype="float32", param_dtype="float32"))
        # btf: disable=BTF006 replicas must share one identical param tree (KV bytes interchangeable)
        params = model.init(jax.random.PRNGKey(0))
    roles = ["prefill"] * n_pre + ["decode"] * n_dec
    if not roles:
        raise ValueError("empty topology")
    if n_pre == 0:  # '4' shorthand: a role-less pool
        roles = ["both"] * n_dec
    tier_index: dict = {}
    replicas = []
    for role in roles:
        idx = tier_index.get(role, 0)
        tier_index[role] = idx + 1
        replicas.append(start_replica(
            model, params, role, page_size=page_size,
            max_batch=max_batch, max_seq=max_seq,
            num_pages=num_pages, warm=warm,
            warm_len=warm_len, slo_ttft_s=slo_ttft_s,
            slo_itl_s=slo_itl_s, chaos=chaos, chaos_index=idx))
    registry = MetricsRegistry()
    pool = ReplicaPool([r.rid for r in replicas],
                       probe_interval=probe_interval, registry=registry,
                       scrape_metrics=True)
    policy = PrefixAffinityPolicy(pool, page_size=page_size,
                                  affinity_blocks=affinity_blocks)
    cp_state = ControlPlaneState(pool, policy, registry=registry,
                                 read_timeout=120.0,
                                 disagg_threshold=disagg_threshold,
                                 slo_ttft_s=slo_ttft_s,
                                 slo_itl_s=slo_itl_s,
                                 chaos=chaos)
    pool.probe_all()  # learn roles before the first request routes
    pool.start()
    cp_httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                   make_fleet_handler(cp_state))
    threading.Thread(target=cp_httpd.serve_forever, daemon=True).start()
    return FleetHandle(replicas, cp_state, cp_httpd)
