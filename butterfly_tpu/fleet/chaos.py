"""Seeded fault injection for the serving fleet (the chaos harness).

The fleet's failure matrix (docs/fleet.md) was hand-tested: kill a
replica here, wedge one there, eyeball the fallback. This module makes
those faults *injectable, deterministic, and countable* so a soak can
assert the system-level property — every submitted request reaches a
terminal outcome (tokens, 429, or 504) — instead of hoping the right
failure happened to fire.

A ``ChaosPlan`` is a seeded list of fault rules. Each rule scopes a
fault to a *target* (replica role, ``role:index``, exact ``host:port``
rid, or ``*``), an *endpoint* (path, or ``*`` for any path except
``/health`` — liveness probing stays honest unless a rule names
``/health`` explicitly), an injection *probability*, and a *count*
budget. Rules draw from their OWN ``random.Random(seed, rule index)``
stream, so the decision sequence is a pure function of (plan JSON,
seed, sequence of matching calls) — the determinism test replays a
call sequence and gets byte-identical injections.

Fault kinds:

=============  =============================================================
``delay``      sleep ``delay_s`` before serving normally (slow replica)
``error``      respond 500 with a JSON error body (application fault)
``wedge``      respond 503 (the heartbeat-latch shape the router retries
               and degrades on)
``drop``       close the socket before any response byte (SIGKILL between
               accept and response — the proxy's refused/garbled path)
``truncate``   send a 200 status claiming a longer body than is written,
               then close mid-body (replica death mid-response; the
               proxy's buffer-before-first-client-byte path)
``slow_stream``serve normally but throttle every response write by
               ``delay_s`` (stuck-but-alive replica; read-timeout path)
=============  =============================================================

Hook points:

* the in-process harness (fleet/harness.py) wraps each replica's HTTP
  handler in :func:`make_chaos_handler` (``where="replica"``);
* the control plane's ``_call`` consults the plan before every handoff
  leg (``where="call"`` — the "network between control plane and
  replica" faults: ``delay`` sleeps, ``drop`` fails the leg as a
  transport error, feeding the same pool/breaker accounting a real
  refused connect would).

Driven by ``butterfly fleet --chaos plan.json`` and the chaos soak in
tests/test_fleet.py / obs/benchmark.py:run_chaos_benchmark.

stdlib-only (importable without jax, like the rest of the router tier).
"""
from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

KINDS = ("delay", "error", "wedge", "drop", "truncate", "slow_stream")
WHERES = ("replica", "call")


class ChaosIdent:
    """Who a fault-plan target matches against: one replica's identity
    as the harness knows it (role + index within the role + bound rid).
    The rid is only known after the port binds, so plans usually target
    roles ('prefill', 'decode:1') which are stable across runs."""

    __slots__ = ("rid", "role", "index")

    def __init__(self, rid: str = "", role: str = "both", index: int = 0):
        self.rid = rid
        self.role = role
        self.index = index

    def matches(self, target: str) -> bool:
        return target in ("*", self.role, f"{self.role}:{self.index}",
                          self.rid)


class FaultRule:
    """One scoped fault. Draws come from a per-rule seeded stream so
    adding/removing one rule never perturbs another's decisions."""

    __slots__ = ("kind", "target", "endpoint", "where", "p", "count",
                 "delay_s", "rng", "injected")

    def __init__(self, kind: str, target: str = "*", endpoint: str = "*",
                 where: str = "replica", p: float = 1.0,
                 count: Optional[int] = None, delay_s: float = 0.05,
                 seed: int = 0, index: int = 0):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(expected one of {KINDS})")
        if where not in WHERES:
            raise ValueError(f"unknown fault scope {where!r} "
                             f"(expected one of {WHERES})")
        if not 0.0 <= float(p) <= 1.0:
            raise ValueError(f"fault probability must be in [0, 1], got {p}")
        if count is not None and int(count) < 1:
            raise ValueError(f"fault count must be >= 1, got {count}")
        self.kind = kind
        self.target = str(target)
        self.endpoint = str(endpoint)
        self.where = where
        self.p = float(p)
        self.count = None if count is None else int(count)
        self.delay_s = float(delay_s)
        # Independent stream per rule: (seed, index) — deterministic
        # regardless of how other rules draw.
        self.rng = random.Random((int(seed) << 16) ^ index)
        self.injected = 0

    def spec(self) -> Dict:
        return {"kind": self.kind, "target": self.target,
                "endpoint": self.endpoint, "where": self.where,
                "p": self.p, "count": self.count, "delay_s": self.delay_s,
                "injected": self.injected}


class Injection:
    """One decided fault (what a hook applies)."""

    __slots__ = ("kind", "delay_s", "rule")

    def __init__(self, rule: FaultRule):
        self.kind = rule.kind
        self.delay_s = rule.delay_s
        self.rule = rule


class ChaosPlan:
    """A seeded, deterministic fault plan.

    ``decide(ident, endpoint, where)`` is the single decision point:
    first matching rule with remaining budget draws from its stream;
    a draw below ``p`` consumes one count and returns an Injection.
    Thread-safe (one lock around the draw + budget), and the decision
    sequence per rule is deterministic given the same sequence of
    matching calls — concurrent soaks inject the same fault *set* up
    to arrival-order interleaving; the determinism test drives calls
    sequentially for byte-identical replay.
    """

    def __init__(self, rules: List[Dict], seed: int = 0):
        self.seed = int(seed)
        self.rules = [FaultRule(seed=self.seed, index=i, **r)
                      for i, r in enumerate(rules)]
        self._lock = threading.Lock()
        self.log: List[Dict] = []  # bounded injection log (tests/state)

    @classmethod
    def from_json(cls, obj: Dict) -> "ChaosPlan":
        if not isinstance(obj, dict) or "faults" not in obj:
            raise ValueError('chaos plan must be {"seed": int, '
                             '"faults": [{...}, ...]}')
        return cls(list(obj["faults"]), seed=int(obj.get("seed", 0)))

    @classmethod
    def from_file(cls, path: str) -> "ChaosPlan":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_json(json.load(f))

    def decide(self, ident: ChaosIdent, endpoint: str,
               where: str = "replica") -> Optional[Injection]:
        path = endpoint.split("?")[0]
        with self._lock:
            for rule in self.rules:
                if rule.where != where:
                    continue
                if not ident.matches(rule.target):
                    continue
                if rule.endpoint == "*":
                    # '*' never matches /health: a plan that silently
                    # wedged liveness probing would fail the pool, not
                    # the path under test. Name /health to chaos it.
                    if path == "/health":
                        continue
                elif path != rule.endpoint:
                    continue
                if rule.count is not None and rule.injected >= rule.count:
                    continue
                if rule.rng.random() >= rule.p:
                    # the draw is consumed either way (determinism), the
                    # budget only on injection
                    continue
                rule.injected += 1
                if len(self.log) < 4096:
                    self.log.append({"target": ident.rid or ident.role,
                                     "endpoint": path, "kind": rule.kind,
                                     "where": where})
                return Injection(rule)
        return None

    @property
    def total_injected(self) -> int:
        with self._lock:
            return sum(r.injected for r in self.rules)

    def summary(self) -> Dict:
        """The /fleet/state chaos block: per-rule specs + totals."""
        with self._lock:
            return {"seed": self.seed,
                    "rules": [r.spec() for r in self.rules],
                    "total_injected": sum(r.injected for r in self.rules)}


def default_plan(seed: int = 0) -> ChaosPlan:
    """The stock soak plan (bench + `butterfly fleet --chaos default`):
    a slow replica, an application 500, a wedged 503 burst long enough
    to trip the control plane's circuit breaker, a mid-accept drop, a
    truncated body, and a dropped control-plane leg — every row of the
    docs/fleet.md failure matrix that can fire without killing a
    process.

    The envelope deliberately leaves each tier a healthy member: every
    decode-tier fault is confined to decode:0 (decode:1 absorbs), and
    prefill-tier faults only cost a handoff fallback. That is the
    chaos contract under test — with a routable quorum, every client
    request must still reach a terminal outcome (tokens, 429, or 504);
    fault BOTH members of a tier at once and the honest answer becomes
    a 502, which is the rolling-drain soak's one-at-a-time rule, not a
    bug."""
    return ChaosPlan([
        {"kind": "delay", "target": "prefill", "endpoint": "/generate",
         "p": 0.3, "count": 4, "delay_s": 0.05},
        {"kind": "error", "target": "prefill:0", "endpoint": "/generate",
         "p": 0.3, "count": 2},
        {"kind": "wedge", "target": "decode:0", "endpoint": "/generate",
         "p": 1.0, "count": 4},
        {"kind": "drop", "target": "prefill", "endpoint": "/generate",
         "p": 0.2, "count": 2},
        {"kind": "truncate", "target": "prefill:0",
         "endpoint": "/generate", "p": 0.2, "count": 1},
        {"kind": "drop", "target": "prefill", "endpoint": "/generate",
         "where": "call", "p": 0.5, "count": 2},
    ], seed=seed)


# -- the replica-side hook ---------------------------------------------------

class _ThrottledWriter:
    """wfile wrapper: sleep before every write (the slow_stream fault).
    Headers and body alike — a stuck-but-alive replica is slow at
    everything. Unknown attributes (closed, fileno, ...) delegate to
    the real file: the http.server plumbing touches more than write()."""

    def __init__(self, wfile, delay_s: float):
        self._w = wfile
        self._delay = delay_s

    def write(self, data):
        time.sleep(self._delay)
        return self._w.write(data)

    def __getattr__(self, name):
        return getattr(self._w, name)


def make_chaos_handler(base_handler_cls, plan: ChaosPlan,
                       ident: ChaosIdent):
    """Wrap a serve-replica handler class: every GET/POST first asks the
    plan for an injection. Faults that replace the response (error /
    wedge / drop / truncate) short-circuit; delay / slow_stream fall
    through to the real handler."""

    class ChaosHandler(base_handler_cls):

        def _chaos(self) -> bool:
            """Apply any decided injection. True = request consumed."""
            inj = plan.decide(ident, self.path, where="replica")
            if inj is None:
                return False
            if inj.kind == "delay":
                time.sleep(inj.delay_s)
                return False
            if inj.kind == "slow_stream":
                self.wfile = _ThrottledWriter(self.wfile, inj.delay_s)
                return False
            if inj.kind in ("error", "wedge"):
                code = 500 if inj.kind == "error" else 503
                body = json.dumps(
                    {"error": f"chaos: injected {inj.kind}"}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return True
            if inj.kind == "drop":
                # no status line at all: the client's HTTP layer sees a
                # reset/garbled connect — the proxy's refused path
                self.close_connection = True
                try:
                    self.connection.close()
                except OSError:
                    pass
                return True
            # truncate: a plausible 200 whose body dies mid-write. The
            # canned body stands in for the real one — from the peer's
            # side the failure is identical (Content-Length underrun).
            claimed = 4096
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(claimed))
            self.end_headers()
            try:
                self.wfile.write(b'{"tokens": [')
                self.wfile.flush()
            except OSError:
                pass
            self.close_connection = True
            try:
                self.connection.close()
            except OSError:
                pass
            return True

        def do_POST(self):
            if not self._chaos():
                base_handler_cls.do_POST(self)

        def do_GET(self):
            if not self._chaos():
                base_handler_cls.do_GET(self)

    return ChaosHandler
