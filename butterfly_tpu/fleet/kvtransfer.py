"""Cross-replica KV page transfer: chain-hash-addressed export/import.

The prefix cache (cache/prefix.py) already makes every registered KV
page content-addressable: page i's key is a SHA-256 chain digest
committing to all tokens of blocks 0..i. This module serializes those
pages between replicas by that key — the replica half of the control
plane's disaggregated prefill/decode handoff (fleet/controlplane.py):

* ``export_payload(sched, hex_hashes)`` — resolve the requested chain
  on the LOCAL registry, pin the matched leading run against eviction,
  read the page contents to the host in one gather, unpin, and return
  a JSON-safe payload (base64 page bytes + dtype/shape metadata +
  geometry). When the replica carries a host KV tier
  (cache/hosttier.py) the leading run continues from it where the
  device registry misses — evicted chains stay exportable. Hashes past
  the first miss of BOTH tiers are reported ``missing`` — pages behind
  a gap could never be attached by ``admit`` anyway.
* ``import_payload(sched, payload)`` — validate geometry (page size,
  layer/head/dim counts, dtype, quantization MUST match; a mismatched
  import would alias garbage K/V under a valid-looking hash), claim
  free pages via ``import_page`` in chain order, scatter the bytes into
  the local pool, and leave the pages warm in the registry so the next
  admission of the same prefix hits them like any local prefix-cache
  entry.

Correctness never depends on a transfer landing: an evicted / missing /
partially imported chain just means the decode replica prefills the
uncovered tail itself. Both sides run under the serving lock
(serve/server.py handler threads), so the scheduler thread can neither
donate the pools mid-read nor recycle a page mid-write.
"""
from __future__ import annotations

import base64
from typing import Dict, List

import numpy as np

PAYLOAD_VERSION = 1


def _enc(a: np.ndarray) -> Dict:
    return {"b64": base64.b64encode(np.ascontiguousarray(a).tobytes())
            .decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _dec(obj: Dict) -> np.ndarray:
    return np.frombuffer(base64.b64decode(obj["b64"]),
                         dtype=np.dtype(obj["dtype"])
                         ).reshape(obj["shape"])


def _geometry(sched) -> Dict:
    cache = sched.engine.cache
    return {
        "page_size": cache.page_size,
        "num_layers": int(cache.k_pages.shape[0]),
        "num_kv_heads": int(cache.k_pages.shape[2]),
        "head_dim": int(cache.k_pages.shape[4]),
        "dtype": str(np.dtype(cache.k_pages.dtype)),
        "quantized": bool(cache.quantized),
    }


def _registry_alloc(sched):
    """The scheduler's allocator, iff it carries the prefix registry the
    transfer is keyed by (prefix_caching on)."""
    alloc = sched.alloc
    if not hasattr(alloc, "lookup"):
        raise LookupError(
            "KV transfer requires prefix caching (--prefix-caching): "
            "without the content-hash page registry there is nothing "
            "to address pages by")
    return alloc


def export_payload(sched, hex_hashes: List[str]) -> Dict:
    """Serialize the leading registered run of `hex_hashes` from
    `sched`'s page pool. Caller holds the serving lock."""
    alloc = _registry_alloc(sched)
    hashes = [bytes.fromhex(h) for h in hex_hashes]
    matched: List[int] = []
    for h in hashes:
        pid = alloc.lookup(h)
        if pid is None:
            break
        matched.append(pid)
    # continue the leading run from the host tier (cache/hosttier.py):
    # a chain this replica evicted to host DRAM is still exportable —
    # the bytes are already host-resident, so no device read is needed
    # for the continuation. Chain contiguity holds: the tier pages
    # start exactly where the device registry missed.
    tier = getattr(sched, "host_tier", None)
    tier_pages: List[tuple] = []
    if tier is not None:
        for i in range(len(matched), len(hashes)):
            data = tier.load(hashes[i])
            if data is None:
                break
            tier_pages.append((hex_hashes[i], data))
    payload: Dict = {
        "version": PAYLOAD_VERSION,
        "meta": _geometry(sched),
        "pages": [],
        "missing": hex_hashes[len(matched) + len(tier_pages):],
        "bytes": 0,
    }
    if not matched and not tier_pages:
        return payload
    total = 0
    if matched:
        # pin the whole run before any device read: the gather below
        # may release the GIL, and an admission on the scheduler thread
        # (once the lock is handed back between chunked exports) must
        # never recycle a page mid-transfer
        alloc.pin(matched)
        try:
            k, v, ks, vs = sched.engine.read_pages(matched)
        finally:
            alloc.unpin(matched)
        for i, h in enumerate(hex_hashes[:len(matched)]):
            entry = {"hash": h, "k": _enc(k[:, i]), "v": _enc(v[:, i])}
            total += k[:, i].nbytes + v[:, i].nbytes
            if ks is not None:
                entry["k_scale"] = _enc(ks[:, i])
                entry["v_scale"] = _enc(vs[:, i])
                total += ks[:, i].nbytes + vs[:, i].nbytes
            payload["pages"].append(entry)
    for h, (k1, v1, ks1, vs1) in tier_pages:
        entry = {"hash": h, "k": _enc(k1), "v": _enc(v1)}
        total += k1.nbytes + v1.nbytes
        if ks1 is not None:
            entry["k_scale"] = _enc(ks1)
            entry["v_scale"] = _enc(vs1)
            total += ks1.nbytes + vs1.nbytes
        payload["pages"].append(entry)
    payload["bytes"] = total
    return payload


def import_payload(sched, payload: Dict) -> Dict:
    """Land an export_payload into `sched`'s pool + prefix registry.
    Caller holds the serving lock. Raises ValueError on geometry
    mismatch (nothing imported); page exhaustion mid-chain stops the
    import with the leading run landed (reported ``no_space``)."""
    alloc = _registry_alloc(sched)
    if int(payload.get("version", -1)) != PAYLOAD_VERSION:
        raise ValueError(f"unsupported KV payload version "
                         f"{payload.get('version')!r}")
    meta, local = payload.get("meta", {}), _geometry(sched)
    bad = {k: (meta.get(k), local[k]) for k in local
           if meta.get(k) != local[k]}
    if bad:
        raise ValueError(
            "KV geometry mismatch (theirs vs ours): "
            + ", ".join(f"{k}={a!r}/{b!r}" for k, (a, b) in bad.items()))
    imported = skipped = 0
    no_space = False
    pids: List[int] = []
    ks_list, vs_list = [], []
    k_list, v_list = [], []
    for entry in payload.get("pages", ()):
        h = bytes.fromhex(entry["hash"])
        try:
            pid = alloc.import_page(h)
        except MemoryError:
            no_space = True
            break  # chain order: what landed is a usable leading run
        if pid is None:
            skipped += 1
            continue
        pids.append(pid)
        k_list.append(_dec(entry["k"]))
        v_list.append(_dec(entry["v"]))
        if local["quantized"]:
            ks_list.append(_dec(entry["k_scale"]))
            vs_list.append(_dec(entry["v_scale"]))
        imported += 1
    if pids:
        # one stacked scatter: [L, n, Kv, page, H] in page order
        k = np.stack(k_list, axis=1)
        v = np.stack(v_list, axis=1)
        ks = np.stack(ks_list, axis=1) if ks_list else None
        vs = np.stack(vs_list, axis=1) if vs_list else None
        sched.engine.write_pages(pids, k, v, ks, vs)
    return {"imported": imported, "skipped": skipped,
            "no_space": no_space, "free_pages": alloc.free_pages}
