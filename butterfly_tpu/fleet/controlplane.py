"""Fleet control plane: the router tier grown KV-aware.

Extends the multi-replica router (router/proxy.py) into the
disaggregated prefill/decode architecture (DistServe OSDI'24 /
Mooncake FAST'25): the compute-bound prefill phase and the
latency-bound decode phase interfere when they share a replica — a
long prompt's prefill stalls every decoding request's next token — so
the control plane runs them on separate replica tiers and streams the
KV state between them by content hash.

Request path (``POST /generate``, token-id body, non-streaming):

1. **classify** — predicted prefill cost = prompt tokens minus the
   tokens expected warm on the decode tier (the affinity ring is the
   predictor: a prefix population routed before has its shared head
   registered on its ring target). Below ``disagg_threshold``, or for
   string prompts (the control plane cannot compute the replicas'
   token-block hashes without a tokenizer), streaming, or
   ``/v1/completions``, the request dispatches DIRECT to the decode
   tier through the inherited router proxy — affinity, failover, and
   the single retry rule all unchanged.
2. **prefill leg** — the request runs on a prefill-role replica with
   ``max_tokens=1``: full prompt prefill + the first token. TTFT is
   measured here, across the handoff.
3. **KV transfer** — the prompt's chain hashes
   (cache/prefix.py:chain_block_hashes — the very keys the replica
   registries use) are exported from the prefill replica
   (``GET /kv/pages``) and imported into the chosen decode replica
   (``POST /kv/import``) verbatim; the pages land warm in its prefix
   registry.
4. **decode leg** — generation resumes on the decode replica with
   prompt = original + first token: admission prefix-hits the imported
   pages and prefills only the partial trailing block, then decodes to
   budget. Greedy outputs are byte-identical to single-replica serving
   (the warm-prefill parity contract).

Every leg degrades safely: a failed export/import just means the
decode replica prefills the whole prompt itself; a failed prefill or
decode leg falls back to a direct dispatch (no client byte has been
sent before the combined response). Correctness never depends on a
transfer landing.

Fleet state: the pool's existing /health probe loop now carries role,
free_pages, and inflight_depth per replica (serve/server.py), so
``GET /fleet/state`` and the placement decision read one table with no
second poll path.

stdlib-only, like the rest of the router tier.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from butterfly_tpu.cache.prefix import chain_block_hashes
from butterfly_tpu.obs.registry import LATENCY_BUCKETS, MetricsRegistry
from butterfly_tpu.router.policy import PrefixAffinityPolicy, affinity_key
from butterfly_tpu.router.pool import Replica, ReplicaPool
from butterfly_tpu.router.proxy import (
    RouterState, extract_route_tokens, make_router_handler)


class ControlPlaneState(RouterState):
    """RouterState plus the disaggregation planner's knobs and the
    fleet_* instrument families."""

    def __init__(self, pool: ReplicaPool, policy: PrefixAffinityPolicy,
                 registry: Optional[MetricsRegistry] = None,
                 read_timeout: float = 300.0,
                 disagg_threshold: int = 64,
                 handoff_timeout: float = 60.0):
        super().__init__(pool, policy, registry=registry,
                         read_timeout=read_timeout)
        self.page_size = policy.page_size
        # predicted FRESH prefill tokens at which a request is worth
        # the handoff (two extra HTTP round trips + the page bytes)
        self.disagg_threshold = max(1, int(disagg_threshold))
        self.handoff_timeout = handoff_timeout
        # prefix populations seen before (affinity key -> True),
        # bounded LRU: the shared head of a repeat population is
        # expected warm on its ring target, shrinking the predicted
        # prefill cost so repeat traffic stays on the decode tier
        self._seen: "OrderedDict[bytes, bool]" = OrderedDict()
        self._seen_cap = 4096
        reg = self.registry
        self._c_disagg = reg.counter(
            "fleet_disagg_requests_total",
            "Requests served via the prefill->transfer->decode handoff")
        self._c_direct = reg.counter(
            "fleet_direct_requests_total",
            "Requests dispatched directly to the decode tier")
        self._c_fallback = reg.counter(
            "fleet_disagg_fallbacks_total",
            "Handoffs that fell back to a direct dispatch mid-flight "
            "(prefill leg, transfer, or decode leg failed)")
        self._c_xfer_bytes = reg.counter(
            "fleet_kv_transfer_bytes_total",
            "Raw KV page bytes exported across replicas")
        self._c_xfer_pages = reg.counter(
            "fleet_kv_transfer_pages_total",
            "KV pages landed into decode-tier prefix registries")
        self._c_xfer_hits = reg.counter(
            "fleet_kv_transfer_hits_total",
            "Requested chain hashes the prefill replica had registered")
        self._c_xfer_miss = reg.counter(
            "fleet_kv_transfer_misses_total",
            "Requested chain hashes missing at export (evicted or "
            "never registered) — the decode replica prefills those "
            "blocks itself")
        self._h_ttft = reg.histogram(
            "fleet_ttft_seconds",
            "Control-plane TTFT for disaggregated requests: client "
            "arrival to the prefill leg's first token, across the "
            "handoff", LATENCY_BUCKETS)

    # -- planning -----------------------------------------------------------

    def direct_plan(self, tokens) -> Tuple[List[Replica], Optional[str]]:
        """Decode-tier candidates (any-role fallback when the decode
        tier is empty/unroutable — a degraded fleet still serves)."""
        cands, aff = self.policy.plan(tokens, role="decode")
        if not cands:
            cands, aff = self.policy.plan(tokens)
        return cands, aff

    def predicted_cost(self, ids: List[int]) -> int:
        """Predicted FRESH prefill tokens: prompt length minus the
        shared head expected warm on the decode tier (affinity-ring
        populations seen before). A heuristic, deliberately cheap —
        misprediction costs only placement, never correctness."""
        key = affinity_key(ids, self.page_size, self.policy.affinity_blocks)
        warm = 0
        with self._mlock:
            seen = key is not None and key in self._seen
            if seen:
                self._seen.move_to_end(key)
        if seen:
            warm = min((len(ids) - 1) // self.page_size,
                       self.policy.affinity_blocks) * self.page_size
        return len(ids) - warm

    def note_seen(self, ids: List[int]) -> None:
        key = affinity_key(ids, self.page_size, self.policy.affinity_blocks)
        if key is None:
            return
        with self._mlock:
            self._seen[key] = True
            self._seen.move_to_end(key)
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)

    def observe(self, hist, v: float) -> None:
        with self._mlock:
            hist.observe(v)

    def add(self, counter, n: float) -> None:
        """Locked multi-increment (instruments are multi-writer here —
        handler threads — like every RouterState update)."""
        with self._mlock:
            counter.inc(n)

    def fleet_counters(self) -> Dict[str, float]:
        hits = self._c_xfer_hits.value
        miss = self._c_xfer_miss.value
        return {
            "disagg_requests": self._c_disagg.value,
            "direct_requests": self._c_direct.value,
            "disagg_fallbacks": self._c_fallback.value,
            "kv_transfer_bytes": self._c_xfer_bytes.value,
            "kv_transfer_pages": self._c_xfer_pages.value,
            "kv_transfer_hits": hits,
            "kv_transfer_misses": miss,
            "kv_transfer_hit_rate":
                hits / (hits + miss) if hits + miss else 0.0,
        }

    def fleet_state(self) -> Dict:
        """The GET /fleet/state body: per-replica placement signals
        (role, liveness, queue depth, page headroom, pipeline depth —
        all from the ONE /health probe loop), the tier membership view
        the planner routes by, and the fleet counters."""
        snaps = self.pool.snapshot()
        tiers = {
            tier: [s["replica"] for s in snaps
                   if s["role"] in (tier, "both")]
            for tier in ("prefill", "decode")
        }
        return {"replicas": snaps, "tiers": tiers,
                "disagg_threshold": self.disagg_threshold,
                "metrics": self.fleet_counters()}


def make_fleet_handler(state: ControlPlaneState):
    """The control-plane HTTP handler: the router handler (proxy,
    admin drain/undrain, /metrics, /router/replicas) plus /fleet/state
    and the disaggregated dispatch path."""
    Base = make_router_handler(state)

    class FleetHandler(Base):

        def do_GET(self):
            if self.path.split("?")[0] == "/fleet/state":
                self._json(200, state.fleet_state())
            else:
                Base.do_GET(self)

        # -- classification ---------------------------------------------------

        def _proxy(self, path: str) -> None:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
            except (ValueError, OSError):
                self._json(400, {"error": "unreadable body"})
                return
            try:
                obj = json.loads(body or b"{}")
            except (ValueError, UnicodeDecodeError):
                obj = None
            ids = self._token_ids(obj)
            plan = self._disagg_plan(path, obj, ids)
            if plan is None:
                state.inc(state._c_direct)
                if ids:
                    state.note_seen(ids)
                route_tokens = extract_route_tokens(body)
                self._dispatch(path, body,
                               *state.direct_plan(route_tokens))
                return
            pre, dec = plan
            self._disaggregate(obj, ids, pre, dec)

        def _token_ids(self, obj) -> Optional[List[int]]:
            """Explicit token ids only: a string prompt would hash its
            UTF-8 bytes, which can never match the replicas'
            tokenized page blocks — such requests route direct."""
            if not isinstance(obj, dict):
                return None
            ids = obj.get("tokens")
            if ids is None and isinstance(obj.get("prompt"), list):
                ids = obj["prompt"]
            if not isinstance(ids, list) or not ids:
                return None
            try:
                return [int(t) for t in ids]
            except (ValueError, TypeError):
                return None

        def _disagg_plan(self, path, obj, ids
                         ) -> Optional[Tuple[Replica, Replica]]:
            """(prefill replica, decode replica) when the handoff is
            worth it, else None -> direct dispatch."""
            if path != "/generate" or not isinstance(obj, dict) \
                    or obj.get("stream") or ids is None:
                return None
            if len(ids) < state.page_size + 1:
                return None  # no full page to transfer
            if state.predicted_cost(ids) < state.disagg_threshold:
                return None
            dec_cands, _ = state.policy.plan(ids, role="decode")
            pre_cands, _ = state.policy.plan(ids, role="prefill")
            if not dec_cands or not pre_cands:
                return None
            dec = dec_cands[0]
            # a handoff to yourself is just a slower direct dispatch
            pre = next((r for r in pre_cands if r.rid != dec.rid), None)
            if pre is None:
                return None
            return pre, dec

        # -- the handoff ------------------------------------------------------

        def _call(self, rep: Replica, method: str, path: str,
                  obj=None, timeout: Optional[float] = None):
            """One control-plane HTTP call with pool feedback. Returns
            (status, parsed body) — status None on transport failure."""
            url = f"http://{rep.host}:{rep.port}{path}"
            data = json.dumps(obj).encode() if obj is not None else None
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={"Content-Type": "application/json"})
            state.pool.note_dispatch(rep.rid)
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or state.read_timeout) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read() or b"{}")
                except (ValueError, OSError):
                    body = {}
                e.close()
                if e.code == 503:
                    state.pool.note_wedged(rep.rid, "503 during handoff")
                return e.code, body
            except Exception as e:  # refused / reset / timeout / bad JSON
                state.pool.note_connect_failure(rep.rid, str(e))
                return None, {"error": str(e)}
            finally:
                state.pool.note_done(rep.rid)

        def _fallback(self, obj, ids) -> None:
            """A handoff leg failed before any client byte: re-dispatch
            the ORIGINAL request direct (the decode replica recomputes
            the whole prompt — slower, never wrong)."""
            state.inc(state._c_fallback)
            body = json.dumps(obj).encode()
            self._dispatch("/generate", body, *state.direct_plan(ids))

        def _disaggregate(self, obj: dict, ids: List[int],
                          pre: Replica, dec: Replica) -> None:
            t0 = time.monotonic()
            state.inc(state._c_disagg)
            max_tokens = int(obj.get("max_tokens",
                                     obj.get("max_new_tokens", 64)))
            # 1. prefill leg: full prompt + first token on the prefill tier
            a_req = {"tokens": ids, "max_tokens": 1}
            for k in ("temperature", "stop_token", "request_id"):
                if k in obj:
                    a_req[k] = obj[k]
            code, a = self._call(pre, "POST", "/generate", a_req,
                                 timeout=state.handoff_timeout)
            if code != 200 or not a.get("tokens"):
                self._fallback(obj, ids)
                return
            ttft = time.monotonic() - t0
            state.observe(state._h_ttft, ttft)
            first = [int(t) for t in a["tokens"]]
            # 2. KV transfer: the prompt's full-page chain, A -> B.
            # Failures are absorbed — B prefills uncovered blocks itself.
            imported = 0
            hashes = [h.hex() for h in chain_block_hashes(ids,
                                                          state.page_size)]
            if hashes:
                code, exp = self._call(
                    pre, "GET", "/kv/pages?hashes=" + ",".join(hashes),
                    timeout=state.handoff_timeout)
                if code == 200:
                    n_pages = len(exp.get("pages", ()))
                    state.add(state._c_xfer_hits, n_pages)
                    state.add(state._c_xfer_miss,
                              len(exp.get("missing", ())))
                    state.add(state._c_xfer_bytes,
                              int(exp.get("bytes", 0)))
                    if n_pages:
                        code, imp = self._call(dec, "POST", "/kv/import",
                                               exp,
                                               timeout=state.handoff_timeout)
                        if code == 200:
                            # skipped = already cached on B (an earlier
                            # transfer or B's own traffic): warm either
                            # way, the handoff's purpose
                            imported = int(imp.get("imported", 0)) \
                                + int(imp.get("skipped", 0))
                            state.add(state._c_xfer_pages, imported)
            state.note_seen(ids)
            meta = {"disaggregated": True, "prefill_replica": pre.rid,
                    "decode_replica": dec.rid,
                    "kv_pages_imported": imported, "ttft_s": ttft}
            # 3. decode leg: prompt + first token, remaining budget.
            # Admission on B prefix-hits the imported pages and
            # prefills only the partial trailing block.
            if max_tokens <= 1 or a.get("stopped"):
                self._finish_disagg(t0, first, a.get("text", ""),
                                    a.get("stopped", False), meta, dec.rid)
                return
            b_req = {"tokens": ids + first, "max_tokens": max_tokens - 1}
            for k in ("temperature", "stop_token", "top_p", "top_k",
                      "request_id"):
                if k in obj:
                    b_req[k] = obj[k]
            code, b = self._call(dec, "POST", "/generate", b_req)
            if code != 200:
                self._fallback(obj, ids)
                return
            self._finish_disagg(
                t0, first + [int(t) for t in b.get("tokens", ())],
                a.get("text", "") + b.get("text", ""),
                b.get("stopped", False), meta, dec.rid)

        def _finish_disagg(self, t0, tokens, text, stopped, meta,
                           rid) -> None:
            state.count(rid, "ok")
            self._json(200, {
                "tokens": tokens, "text": text, "stopped": stopped,
                "total_s": time.monotonic() - t0, **meta,
            }, headers={"X-Routed-To": rid})

    return FleetHandler


def fleet_forever(backends: List[str], host: str = "0.0.0.0",
                  port: int = 8100, page_size: int = 16,
                  affinity_blocks: int = 4, saturate_after: int = 8,
                  probe_interval: float = 0.5, probe_timeout: float = 2.0,
                  dead_after: int = 3, read_timeout: float = 300.0,
                  disagg_threshold: int = 64,
                  ready_event=None):
    """Blocking control-plane loop (`butterfly route --disaggregate`).
    Same shape as router.proxy.route_forever — the control plane IS the
    router, grown KV-aware."""
    import threading
    from http.server import ThreadingHTTPServer

    registry = MetricsRegistry()
    pool = ReplicaPool(backends, probe_interval=probe_interval,
                       probe_timeout=probe_timeout, dead_after=dead_after,
                       registry=registry)
    policy = PrefixAffinityPolicy(pool, page_size=page_size,
                                  affinity_blocks=affinity_blocks,
                                  saturate_after=saturate_after)
    state = ControlPlaneState(pool, policy, registry=registry,
                              read_timeout=read_timeout,
                              disagg_threshold=disagg_threshold)
    pool.probe_all()   # one synchronous round: roles known at bind
    pool.start()

    class _Server(ThreadingHTTPServer):
        request_queue_size = 128

    httpd = _Server((host, port), make_fleet_handler(state))
    state.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    snaps = pool.snapshot()
    n_pre = sum(1 for s in snaps if s["role"] in ("prefill", "both"))
    n_dec = sum(1 for s in snaps if s["role"] in ("decode", "both"))
    print(f"[butterfly] fleet control plane on {host}:{port}: "
          f"{len(snaps)} replicas ({n_pre} prefill-capable, "
          f"{n_dec} decode-capable), disagg threshold "
          f"{state.disagg_threshold} tokens", flush=True)
    try:
        httpd.serve_forever()
    finally:
        pool.stop()
        httpd.server_close()
    return 0
