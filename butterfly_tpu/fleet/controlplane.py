"""Fleet control plane: the router tier grown KV-aware.

Extends the multi-replica router (router/proxy.py) into the
disaggregated prefill/decode architecture (DistServe OSDI'24 /
Mooncake FAST'25): the compute-bound prefill phase and the
latency-bound decode phase interfere when they share a replica — a
long prompt's prefill stalls every decoding request's next token — so
the control plane runs them on separate replica tiers and streams the
KV state between them by content hash.

Request path (``POST /generate``, token-id body, non-streaming):

1. **classify** — predicted prefill cost = prompt tokens minus the
   tokens expected warm on the decode tier (the affinity ring is the
   predictor: a prefix population routed before has its shared head
   registered on its ring target). Below ``disagg_threshold``, or for
   string prompts (the control plane cannot compute the replicas'
   token-block hashes without a tokenizer), streaming, or
   ``/v1/completions``, the request dispatches DIRECT to the decode
   tier through the inherited router proxy — affinity, failover, and
   the single retry rule all unchanged.
2. **prefill leg** — the request runs on a prefill-role replica with
   ``max_tokens=1``: full prompt prefill + the first token. TTFT is
   measured here, across the handoff.
3. **KV transfer** — the prompt's chain hashes
   (cache/prefix.py:chain_block_hashes — the very keys the replica
   registries use) are exported from the prefill replica
   (``GET /kv/pages``) and imported into the chosen decode replica
   (``POST /kv/import``) verbatim; the pages land warm in its prefix
   registry.
4. **decode leg** — generation resumes on the decode replica with
   prompt = original + first token: admission prefix-hits the imported
   pages and prefills only the partial trailing block, then decodes to
   budget. Greedy outputs are byte-identical to single-replica serving
   (the warm-prefill parity contract).

Every leg degrades safely: a failed export/import just means the
decode replica prefills the whole prompt itself; a failed prefill or
decode leg falls back to a direct dispatch (no client byte has been
sent before the combined response). Correctness never depends on a
transfer landing.

Fleet state: the pool's existing /health probe loop now carries role,
free_pages, and inflight_depth per replica (serve/server.py), so
``GET /fleet/state`` and the placement decision read one table with no
second poll path.

Observability plane (ISSUE 7, docs/observability.md §fleet tracing):

* every proxied request is traced as control-plane LEG spans (classify,
  prefill_leg, kv_export, kv_import, decode_leg / direct_leg, fallback)
  under an ``X-Request-Id`` the handler mints when the client didn't,
  and forwards on EVERY leg — so each replica's own tracer keys the
  same id. ``GET /fleet/trace?request_id=`` joins the legs with the
  involved replicas' timelines (``/debug/requests?request_id=``) on one
  clock, using the per-replica clock offset the health prober estimates
  from the probe RTT midpoint.
* the prober also scrapes each replica's ``/metrics``;
  ``GET /fleet/metrics`` re-exports the fleet rollup — counters summed,
  histograms re-bucketed exactly (fixed shared ladders), per-replica
  autoscale gauges labeled ``{replica=...}``.
* declared SLOs (``--slo-ttft-ms`` / ``--slo-itl-ms``) are measured
  across the whole handoff into ``fleet_slo_*`` counters and a rolling
  burn-rate gauge.

stdlib-only, like the rest of the router tier.
"""
from __future__ import annotations

import itertools
import json
import socket
import time
import urllib.error
import urllib.request
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from butterfly_tpu.cache.prefix import chain_block_hashes
from butterfly_tpu.obs.registry import (
    LATENCY_BUCKETS, MetricsRegistry, render_parsed, sum_expositions)
from butterfly_tpu.obs.ticklog import FlightRecorder
from butterfly_tpu.obs.timeseries import (
    FLEET_TIMESERIES_SCHEMA, default_fleet_rules, evaluate_rules)
from butterfly_tpu.obs.trace import Tracer, merge_fleet_trace
from butterfly_tpu.router.policy import PrefixAffinityPolicy, affinity_key
from butterfly_tpu.router.pool import Replica, ReplicaPool
from butterfly_tpu.router.proxy import (
    RouterState, extract_route_tokens, make_router_handler)


class ControlPlaneState(RouterState):
    """RouterState plus the disaggregation planner's knobs and the
    fleet_* instrument families."""

    def __init__(self, pool: ReplicaPool, policy: PrefixAffinityPolicy,
                 registry: Optional[MetricsRegistry] = None,
                 read_timeout: float = 300.0,
                 disagg_threshold: int = 64,
                 handoff_timeout: float = 60.0,
                 slo_ttft_s: Optional[float] = None,
                 slo_itl_s: Optional[float] = None,
                 tracer: Optional[Tracer] = None,
                 chaos=None):
        super().__init__(pool, policy, registry=registry,
                         read_timeout=read_timeout)
        self.page_size = policy.page_size
        # Optional seeded fault plan (fleet/chaos.py ChaosPlan): _call
        # consults it before every handoff leg, so network faults
        # between control plane and replicas are injectable with the
        # same determinism as the replica-side hooks. None = no chaos.
        self.chaos = chaos
        # Control-plane tracer: every proxied request gets a timeline of
        # LEG spans (classify, prefill_leg, kv_export, kv_import,
        # decode_leg, direct_leg, fallback) keyed by the same
        # X-Request-Id the replicas trace under — GET /fleet/trace
        # joins them into one cross-replica waterfall. Tracer's internal
        # lock makes it safe for the handler threads.
        self.tracer = tracer if tracer is not None else Tracer()
        self._trace_ids = itertools.count()
        # declared latency objectives, measured ACROSS the handoff (the
        # latency the client sees, not any single replica's view)
        self.slo_ttft_s = slo_ttft_s
        self.slo_itl_s = slo_itl_s
        self._slo_window: deque = deque(maxlen=256)
        # predicted FRESH prefill tokens at which a request is worth
        # the handoff (two extra HTTP round trips + the page bytes)
        self.disagg_threshold = max(1, int(disagg_threshold))
        self.handoff_timeout = handoff_timeout
        # prefix populations seen before (affinity key -> True),
        # bounded LRU: the shared head of a repeat population is
        # expected warm on its ring target, shrinking the predicted
        # prefill cost so repeat traffic stays on the decode tier
        self._seen: "OrderedDict[bytes, bool]" = OrderedDict()
        self._seen_cap = 4096
        reg = self.registry
        self._c_disagg = reg.counter(
            "fleet_disagg_requests_total",
            "Requests served via the prefill->transfer->decode handoff")
        self._c_direct = reg.counter(
            "fleet_direct_requests_total",
            "Requests dispatched directly to the decode tier")
        self._c_fallback = reg.counter(
            "fleet_disagg_fallbacks_total",
            "Handoffs that fell back to a direct dispatch mid-flight "
            "(prefill leg, transfer, or decode leg failed)")
        self._c_xfer_bytes = reg.counter(
            "fleet_kv_transfer_bytes_total",
            "Raw KV page bytes exported across replicas")
        self._c_xfer_pages = reg.counter(
            "fleet_kv_transfer_pages_total",
            "KV pages landed into decode-tier prefix registries")
        self._c_xfer_hits = reg.counter(
            "fleet_kv_transfer_hits_total",
            "Requested chain hashes the prefill replica had registered")
        self._c_xfer_miss = reg.counter(
            "fleet_kv_transfer_misses_total",
            "Requested chain hashes missing at export (evicted or "
            "never registered) — the decode replica prefills those "
            "blocks itself")
        self._h_ttft = reg.histogram(
            "fleet_ttft_seconds",
            "Control-plane TTFT for disaggregated requests: client "
            "arrival to the prefill leg's first token, across the "
            "handoff", LATENCY_BUCKETS)
        self._c_slo_ttft_ok = reg.counter(
            "fleet_slo_ttft_ok_total",
            "Disaggregated requests whose cross-handoff TTFT met the "
            "declared objective (--slo-ttft-ms on the route CLI)")
        self._c_slo_itl_ok = reg.counter(
            "fleet_slo_itl_ok_total",
            "Disaggregated requests whose mean inter-token gap met the "
            "declared ITL objective")
        self._c_slo_viol = reg.counter_family(
            "fleet_slo_violations_total",
            "Disaggregated requests that missed a declared latency "
            "objective, by objective kind", ("kind",))
        self._g_slo_burn = reg.gauge(
            "fleet_slo_burn_rate",
            "Fraction of the last 256 disaggregated requests that "
            "violated ANY declared objective")
        # classified handoff-leg failures (ISSUE 8 satellite): one
        # series per (leg, kind) instead of a bare except bucket —
        # a dashboard can tell a timing-out prefill tier from a
        # decode tier returning garbage
        self._c_leg_fail = reg.counter_family(
            "fleet_leg_failures_total",
            "Handoff-leg failures by leg (prefill_leg/kv_export/"
            "kv_import/decode_leg) and kind (timeout/refused/"
            "bad_status/bad_body/chaos)", ("leg", "kind"))
        self._c_deadline = reg.counter_family(
            "fleet_deadline_expired_total",
            "Requests whose deadline budget expired at the control "
            "plane, by where (arrival, or the handoff leg about to "
            "run)", ("where",))
        # Control-plane anomaly flight recorder (ISSUE 15): records the
        # fleet-level event classes the replicas can't see — breaker
        # transitions and control-plane deadline 504s — and joins the
        # per-replica rings at GET /fleet/flightrecorder (events
        # shifted onto this process's clock by the health-probe offset,
        # exactly like the fleet trace merge).
        self.flightrec = FlightRecorder()
        pool.on_breaker_open = lambda rid: self.flightrec.note(
            "breaker", replica=rid, transition="open")
        # Per-replica alert rules over the scrape-derived gauge history
        # (ISSUE 16): rules are STATEFUL (rising-edge latch), so each
        # replica gets its own set, built lazily at its first probe.
        # The pool calls the hook outside its lock after every probe;
        # fired alerts land in this flight recorder as `alert` events
        # with the surrounding series attached.
        self._replica_rules: Dict[str, list] = {}
        pool.on_series_sample = self._on_series_sample

    def _on_series_sample(self, rid: str, tail: list,
                          missed: int) -> None:
        rules = self._replica_rules.get(rid)
        if rules is None:
            rules = self._replica_rules[rid] = default_fleet_rules()
        evaluate_rules(rules, tail, flightrec=self.flightrec,
                       source=rid, missing=missed)

    # -- planning -----------------------------------------------------------

    def direct_plan(self, tokens) -> Tuple[List[Replica], Optional[str]]:
        """Decode-tier candidates (any-role fallback when the decode
        tier is empty/unroutable — a degraded fleet still serves)."""
        cands, aff = self.policy.plan(tokens, role="decode")
        if not cands:
            cands, aff = self.policy.plan(tokens)
        return cands, aff

    def predicted_cost(self, ids: List[int]) -> int:
        """Predicted FRESH prefill tokens: prompt length minus the
        shared head expected warm on the decode tier (affinity-ring
        populations seen before). A heuristic, deliberately cheap —
        misprediction costs only placement, never correctness."""
        key = affinity_key(ids, self.page_size, self.policy.affinity_blocks)
        warm = 0
        with self._mlock:
            seen = key is not None and key in self._seen
            if seen:
                self._seen.move_to_end(key)
        if seen:
            warm = min((len(ids) - 1) // self.page_size,
                       self.policy.affinity_blocks) * self.page_size
        return len(ids) - warm

    def note_seen(self, ids: List[int]) -> None:
        key = affinity_key(ids, self.page_size, self.policy.affinity_blocks)
        if key is None:
            return
        with self._mlock:
            self._seen[key] = True
            self._seen.move_to_end(key)
            while len(self._seen) > self._seen_cap:
                self._seen.popitem(last=False)

    def observe(self, hist, v: float) -> None:
        with self._mlock:
            hist.observe(v)

    def add(self, counter, n: float) -> None:
        """Locked multi-increment (instruments are multi-writer here —
        handler threads — like every RouterState update)."""
        with self._mlock:
            counter.inc(n)

    def record_leg_failure(self, leg: str, kind: str) -> None:
        with self._mlock:
            self._c_leg_fail.labels(leg, kind).inc()

    def record_deadline(self, where: str) -> None:
        with self._mlock:
            self._c_deadline.labels(where).inc()
        self.flightrec.note("deadline_504", where=where)
        # expiry-burst trigger: the control plane sees spent-budget
        # storms the replicas never receive (504 before any leg runs)
        self.flightrec.poll({"deadline_expired_total": sum(
            c.value for c in self._c_deadline._children.values())})

    def fleet_counters(self) -> Dict[str, float]:
        hits = self._c_xfer_hits.value
        miss = self._c_xfer_miss.value
        return {
            "disagg_requests": self._c_disagg.value,
            "direct_requests": self._c_direct.value,
            "disagg_fallbacks": self._c_fallback.value,
            "kv_transfer_bytes": self._c_xfer_bytes.value,
            "kv_transfer_pages": self._c_xfer_pages.value,
            "kv_transfer_hits": hits,
            "kv_transfer_misses": miss,
            "kv_transfer_hit_rate":
                hits / (hits + miss) if hits + miss else 0.0,
            "leg_failures": sum(
                c.value for c in self._c_leg_fail._children.values()),
            "deadline_expired": sum(
                c.value for c in self._c_deadline._children.values()),
            "breaker_opens": self.pool.breaker_opens_total(),
        }

    def fleet_state(self) -> Dict:
        """The GET /fleet/state body: per-replica placement signals
        (role, liveness, queue depth, page headroom, pipeline depth —
        all from the ONE /health probe loop), the tier membership view
        the planner routes by, and the fleet counters."""
        snaps = self.pool.snapshot()
        tiers = {
            tier: [s["replica"] for s in snaps
                   if s["role"] in (tier, "both")]
            for tier in ("prefill", "decode")
        }
        out = {"replicas": snaps, "tiers": tiers,
               "disagg_threshold": self.disagg_threshold,
               "slo": {"ttft_s": self.slo_ttft_s,
                       "itl_s": self.slo_itl_s},
               "metrics": self.fleet_counters()}
        if self.chaos is not None:
            out["chaos"] = self.chaos.summary()
        return out

    # -- distributed tracing ------------------------------------------------

    def begin_trace(self, request_id: str, **attrs) -> int:
        """Open a control-plane timeline for one proxied request; the
        returned tid keys this handler's span events. The client
        request id is the cross-replica join key."""
        tid = next(self._trace_ids)
        self.tracer.begin_request(tid, request_id=request_id, **attrs)
        return tid

    def observe_slo(self, ttft_s: Optional[float],
                    itl_mean_s: Optional[float]) -> Dict[str, bool]:
        """Record one disaggregated request's attainment against the
        declared objectives; returns the per-objective verdicts (empty
        when no objective is declared)."""
        out: Dict[str, bool] = {}
        if self.slo_ttft_s is None and self.slo_itl_s is None:
            return out
        viol = False
        with self._mlock:
            if self.slo_ttft_s is not None:
                ok = ttft_s is not None and ttft_s <= self.slo_ttft_s
                out["slo_ttft_ok"] = ok
                (self._c_slo_ttft_ok.inc() if ok
                 else self._c_slo_viol.labels("ttft").inc())
                viol |= not ok
            if self.slo_itl_s is not None and itl_mean_s is not None:
                ok = itl_mean_s <= self.slo_itl_s
                out["slo_itl_ok"] = ok
                (self._c_slo_itl_ok.inc() if ok
                 else self._c_slo_viol.labels("itl").inc())
                viol |= not ok
            self._slo_window.append(1.0 if viol else 0.0)
            self._g_slo_burn.set(sum(self._slo_window)
                                 / len(self._slo_window))
        return out

    def assemble_trace(self, request_id: str) -> Optional[Dict]:
        """The GET /fleet/trace body: this control plane's leg spans for
        `request_id`, joined with every involved replica's own timeline
        (fetched via /debug/requests?request_id=) on ONE clock — each
        replica's monotonic events convert to its wall clock via its
        tracer anchors, then shift by the health-probe clock-offset
        estimate. A replica that is down (or restarted with a fresh
        tracer) degrades to control-plane spans only, with its error
        recorded under `sources`."""
        tl = self.tracer.find_by_request_id(request_id)
        if tl is None:
            return None
        rids: List[str] = []
        for ev in tl["events"]:
            rid = ev.get("replica")
            if rid and rid not in rids:
                rids.append(rid)
        replicas: Dict[str, Dict] = {}
        for rid in rids:
            rep = self.pool.get(rid)
            info: Dict = {"offset_s": rep.clock_offset if rep else None}
            try:
                url = (f"http://{rep.host}:{rep.port}/debug/requests"
                       f"?request_id={request_id}") if rep else None
                if url is None:
                    raise LookupError(f"unknown replica {rid}")
                # the pool's probe timeout governs every control-plane
                # side channel — one knob, no stray hard-coded 5.0
                with urllib.request.urlopen(
                        url, timeout=self.pool.probe_timeout) as resp:
                    info["dump"] = json.loads(resp.read() or b"{}")
            except Exception as e:  # down/restarting: degrade, never 500
                info["dump"] = None
                info["error"] = f"{type(e).__name__}: {e}"
            replicas[rid] = info
        return merge_fleet_trace(
            request_id,
            {"timeline": tl, "t0_wall": self.tracer.t0_wall,
             "t0_monotonic": self.tracer.t0_monotonic},
            replicas)

    # -- fleet metrics rollup -----------------------------------------------

    #: replica flat-dict gauges re-exported per replica from the scrape
    #: (the autoscale signal surface ROADMAP item 3 reads); everything
    #: else gauge-typed is dropped from the rollup — summing uptimes or
    #: queue-depth snapshots across replicas is not a meaningful series.
    AUTOSCALE_GAUGES = ("queue_depth", "active_requests", "kv_pages_free",
                        "kv_pages_total", "inflight_depth",
                        "tokens_per_sec", "device_bubble_p50",
                        "device_bubble_p95", "slo_burn_rate",
                        # tick anatomy (ISSUE 15): host-bound vs
                        # device-bound per replica — an autoscaler that
                        # only sees queue depth can't tell which tier
                        # needs more replicas vs a faster host path
                        "tick_host_frac", "tick_phase_dominant_p95",
                        # host KV tier (ISSUE 17): revive economics per
                        # replica — absent on tier-less replicas (the
                        # re-export skips absent gauges)
                        "kv_tier_hit_rate")

    #: consecutive failed /metrics scrapes after which a replica's
    #: re-exported gauges are DROPPED from /fleet/metrics: a gauge
    #: frozen at its last good value reads as a live flat line to an
    #: autoscaler, which is worse than an absent series. Counters keep
    #: the last good scrape through the outage (a sum that briefly
    #: under-counts then catches up is the normal counter contract).
    SCRAPE_STALE_AFTER = 3

    def fleet_metrics_text(self) -> str:
        """The GET /fleet/metrics body: one exposition aggregating every
        replica's last-scraped /metrics. Counters sum; histograms sum
        bucket-wise (exact — the registry's fixed ladders are identical
        across replicas, and mismatched ladders are dropped rather than
        mis-summed); per-replica autoscale gauges ride along labeled
        {replica="host:port"}. Replica families re-export namespaced
        butterfly_fleet_*."""
        by_rid = self.pool.metrics_by_replica()
        agg = sum_expositions(list(by_rid.values()))

        def rename(name: str) -> str:
            return name.replace("butterfly_", "butterfly_fleet_", 1) \
                if name.startswith("butterfly_") else "fleet_" + name

        lines = render_parsed(agg, rename=rename)
        lines.append("# HELP butterfly_fleet_replicas_scraped Replicas "
                     "contributing to this rollup (last /metrics scrape "
                     "retained through transient failures)")
        lines.append("# TYPE butterfly_fleet_replicas_scraped gauge")
        lines.append(f"butterfly_fleet_replicas_scraped {len(by_rid)}")
        # per-replica autoscale gauges, from each replica's own scrape —
        # minus replicas whose scrapes have been failing (stale-gauge
        # drop: see SCRAPE_STALE_AFTER)
        stale = set(self.pool.stale_scrapes(self.SCRAPE_STALE_AFTER))
        per_rep: Dict[str, List[Tuple[str, float]]] = {}
        for rid, families in sorted(by_rid.items()):
            if rid in stale:
                continue
            for key in self.AUTOSCALE_GAUGES:
                fam = families.get(f"butterfly_{key}")
                if not fam:
                    continue
                v = fam["samples"].get((f"butterfly_{key}", ()))
                if v is not None:
                    per_rep.setdefault(key, []).append((rid, v))
        for key, samples in sorted(per_rep.items()):
            full = f"butterfly_fleet_replica_{key}"
            lines.append(f"# TYPE {full} gauge")
            lines.extend(f'{full}{{replica="{rid}"}} {v:g}'
                         for rid, v in samples)
        return "\n".join(lines) + ("\n" if lines else "")

    # -- fleet flight-recorder rollup ---------------------------------------

    def flightrecorder_rollup(self) -> Dict:
        """The GET /fleet/flightrecorder body: this control plane's own
        anomaly ring (breaker transitions, control-plane 504s) merged
        with every replica's /debug/flightrecorder dump on ONE clock —
        each replica's wall-clock event stamps shift by the clock
        offset the health prober estimated (the PR 7 trace-merge
        timeline), so a fleet-wide anomaly reads as one ordered story.
        Unreachable replicas degrade to an error entry, never a 500."""
        sources: Dict[str, Dict] = {}
        merged: List[Dict] = []
        dumps: List[Dict] = []

        def absorb(src: str, dump: Dict, offset: float) -> None:
            evs = []
            for ev in dump.get("events", ()):
                ev2 = dict(ev)
                ev2["source"] = src
                ev2["t_fleet"] = float(ev.get("t_wall", 0.0)) - offset
                evs.append(ev2)
            merged.extend(evs)
            for art in dump.get("dumps", ()):
                dumps.append({"source": src, "offset_s": offset, **art})
            sources[src] = {"events": len(evs),
                            "dumps": len(dump.get("dumps", ())),
                            "offset_s": offset,
                            "triggers_fired":
                                dump.get("triggers_fired", {})}

        absorb("control", self.flightrec.dump(), 0.0)
        for snap in self.pool.snapshot():
            rid = snap["replica"]
            offset = snap.get("clock_offset_s") or 0.0
            try:
                url = f"http://{rid}/debug/flightrecorder"
                with urllib.request.urlopen(
                        url, timeout=self.pool.probe_timeout) as resp:
                    dump = json.loads(resp.read() or b"{}")
            except Exception as e:  # down/restarting: degrade
                sources[rid] = {"events": 0, "missing": True,
                                "error": f"{type(e).__name__}: {e}"}
                continue
            if not dump.get("enabled"):
                sources[rid] = {"events": 0, "enabled": False}
                continue
            absorb(rid, dump, offset)
        merged.sort(key=lambda ev: ev["t_fleet"])
        return {"sources": sources, "events": merged, "dumps": dumps}

    # -- fleet timeseries rollup --------------------------------------------

    def fleet_timeseries(self) -> Dict:
        """The GET /fleet/timeseries body: every replica's signal
        history merged on ONE clock. Two sample populations per
        replica, both tagged with their source:

        * ``scrape:<rid>`` — the pool's scrape-derived gauge ring,
          stamped on THIS process's wall clock at the probe RTT
          midpoint (offset zero by construction);
        * ``<rid>`` — the replica's own /debug/timeseries dump, its
          wall stamps shifted by the health prober's clock-offset
          estimate (the PR 7 trace-merge timeline).

        Alert events ride along: each replica dump's fired alerts plus
        the control plane's own `alert` flight-recorder events (the
        per-replica flatline/slope rules). Unreachable replicas degrade
        to an error entry, never a 500."""
        sources: Dict[str, Dict] = {}
        merged: List[Dict] = []
        alerts: List[Dict] = []

        def absorb(src: str, samples, offset: float) -> None:
            n = 0
            for s in samples:
                s2 = dict(s)
                s2["source"] = src
                s2["t_fleet"] = float(s.get("t_wall", 0.0)) - offset
                merged.append(s2)
                n += 1
            sources[src] = {"samples": n, "offset_s": offset}

        for rid, ring in sorted(self.pool.series_by_replica().items()):
            absorb(f"scrape:{rid}", ring, 0.0)
        for snap in self.pool.snapshot():
            rid = snap["replica"]
            offset = snap.get("clock_offset_s") or 0.0
            try:
                url = f"http://{rid}/debug/timeseries"
                with urllib.request.urlopen(
                        url, timeout=self.pool.probe_timeout) as resp:
                    dump = json.loads(resp.read() or b"{}")
            except Exception as e:  # down/restarting: degrade
                sources[rid] = {"samples": 0, "missing": True,
                                "error": f"{type(e).__name__}: {e}"}
                continue
            if not dump.get("enabled"):
                sources[rid] = {"samples": 0, "enabled": False}
                continue
            absorb(rid, dump.get("samples", ()), offset)
            for a in dump.get("alerts", ()):
                a2 = dict(a)
                a2.setdefault("source", rid)
                a2["t_fleet"] = float(a.get("t_wall", 0.0)) - offset
                alerts.append(a2)
        for ev in self.flightrec.dump().get("events", ()):
            if ev.get("kind") == "alert":
                a2 = dict(ev)
                a2.setdefault("source", "control")
                a2["t_fleet"] = float(ev.get("t_wall", 0.0))
                alerts.append(a2)
        merged.sort(key=lambda s: s["t_fleet"])
        alerts.sort(key=lambda a: a["t_fleet"])
        return {"schema": FLEET_TIMESERIES_SCHEMA, "sources": sources,
                "samples": merged, "alerts": alerts}


def make_fleet_handler(state: ControlPlaneState):
    """The control-plane HTTP handler: the router handler (proxy,
    admin drain/undrain, /metrics, /router/replicas) plus /fleet/state
    and the disaggregated dispatch path."""
    Base = make_router_handler(state)

    class FleetHandler(Base):

        def do_GET(self):
            path = self.path.split("?")[0]
            if path == "/fleet/state":
                self._json(200, state.fleet_state())
            elif path == "/fleet/trace":
                self._fleet_trace()
            elif path == "/fleet/flightrecorder":
                self._json(200, state.flightrecorder_rollup())
            elif path == "/debug/flightrecorder":
                # the control plane's OWN ring (breaker opens, deadline
                # 504s, autoscaler scale/scale_held decisions) — same
                # shape a replica serves under this path
                self._json(200, state.flightrec.dump())
            elif path == "/fleet/timeseries":
                self._json(200, state.fleet_timeseries())
            elif path == "/fleet/metrics":
                body = state.fleet_metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                Base.do_GET(self)

        def _fleet_trace(self) -> None:
            from urllib.parse import parse_qs, urlparse
            qs = parse_qs(urlparse(self.path).query)
            rid = qs.get("request_id", [None])[0]
            if not rid:
                self._json(400, {"error": "missing ?request_id= (the "
                                          "X-Request-Id / request_id the "
                                          "request was tagged with)"})
                return
            merged = state.assemble_trace(str(rid)[:128])
            if merged is None:
                self._json(404, {"error": f"no control-plane timeline "
                                          f"for request_id {rid!r} "
                                          f"(evicted or never seen)"})
            else:
                self._json(200, merged)

        # -- classification ---------------------------------------------------

        def _ensure_request_id(self, obj) -> str:
            """The distributed trace id: client header wins, then a
            request_id body field, else one is minted. Injected into
            self.headers so the inherited proxy forwards it on direct
            dispatches — every replica then traces under the SAME id
            the control plane does."""
            rid = self.headers.get("X-Request-Id") \
                or (obj.get("request_id") if isinstance(obj, dict)
                    else None)
            rid = str(rid)[:128] if rid else \
                f"fleet-{uuid.uuid4().hex[:12]}"
            if self.headers.get("X-Request-Id") != rid:
                del self.headers["X-Request-Id"]
                self.headers["X-Request-Id"] = rid
            return rid

        def _ensure_deadline(self, obj, t_arrive: float) -> Optional[float]:
            """The request's latency budget as an ABSOLUTE monotonic
            deadline: X-Deadline-Ms header wins, then a deadline_ms
            body field. The value is the REMAINING budget at this hop —
            every forward re-stamps the header with what's left, so the
            budget is consumed across the whole fleet path, not reset
            per process. Malformed values pass through untouched (the
            replica 400s them)."""
            dl = self.headers.get("X-Deadline-Ms")
            if dl is None and isinstance(obj, dict):
                dl = obj.get("deadline_ms")
            if dl is None:
                return None
            try:
                return t_arrive + float(dl) / 1e3
            except (TypeError, ValueError):
                return None

        def _restamp_deadline(self, deadline_s: Optional[float]) -> None:
            """Refresh X-Deadline-Ms to the remaining budget before the
            inherited direct-dispatch proxy forwards the headers."""
            if deadline_s is None:
                return
            rem = max(1, int((deadline_s - time.monotonic()) * 1e3))
            del self.headers["X-Deadline-Ms"]
            self.headers["X-Deadline-Ms"] = str(rem)

        def _deadline_504(self, tid: int, request_id: str,
                          t_arrive: float, where: str,
                          detail: Optional[dict] = None) -> None:
            """Terminal deadline verdict: 504 with where-it-died +
            elapsed, counted and traced. `detail` merges a downstream
            504 body (the replica's own where/elapsed) when the expiry
            happened there."""
            state.record_deadline(where)
            elapsed = time.monotonic() - t_arrive
            state.tracer.event(tid, "finish", state="deadline_expired",
                               where=where, total_s=elapsed)
            body = {"error": "deadline exceeded", "where": where,
                    "elapsed_ms": elapsed * 1e3,
                    "request_id": request_id}
            for k in ("where", "elapsed_ms", "deadline_ms"):
                if detail and k in detail:
                    body[k] = detail[k]
            self._json(504, body)

        def _proxy(self, path: str) -> None:
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
            except (ValueError, OSError):
                self._json(400, {"error": "unreadable body"})
                return
            try:
                obj = json.loads(body or b"{}")
            except (ValueError, UnicodeDecodeError):
                obj = None
            t_arrive = time.monotonic()
            request_id = self._ensure_request_id(obj)
            deadline_s = self._ensure_deadline(obj, t_arrive)
            ids = self._token_ids(obj)
            tid = state.begin_trace(request_id, path=path,
                                    prompt_len=len(ids) if ids else None)
            if deadline_s is not None and t_arrive >= deadline_s:
                # arrived with a spent budget: terminal 504 here — it
                # must not burn a classify, a handoff, or a queue slot
                self._deadline_504(tid, request_id, t_arrive, "arrival")
                return
            plan = self._disagg_plan(path, obj, ids)
            state.tracer.event(
                tid, "classify", dur_s=time.monotonic() - t_arrive,
                decision="disagg" if plan else "direct",
                predicted_cost=state.predicted_cost(ids) if ids else None,
                threshold=state.disagg_threshold)
            if plan is None:
                state.inc(state._c_direct)
                if ids:
                    state.note_seen(ids)
                route_tokens = extract_route_tokens(body)
                self._restamp_deadline(deadline_s)
                t0 = time.monotonic()
                served = self._dispatch(path, body,
                                        *state.direct_plan(route_tokens))
                state.tracer.event(tid, "direct_leg",
                                   dur_s=time.monotonic() - t0,
                                   replica=served,
                                   status="ok" if served else "failed")
                state.tracer.event(tid, "finish", state="direct",
                                   total_s=time.monotonic() - t_arrive)
                return
            pre, dec = plan
            self._disaggregate(obj, ids, pre, dec, tid=tid,
                               request_id=request_id, t_arrive=t_arrive,
                               deadline_s=deadline_s)

        def _token_ids(self, obj) -> Optional[List[int]]:
            """Explicit token ids only: a string prompt would hash its
            UTF-8 bytes, which can never match the replicas'
            tokenized page blocks — such requests route direct."""
            if not isinstance(obj, dict):
                return None
            ids = obj.get("tokens")
            if ids is None and isinstance(obj.get("prompt"), list):
                ids = obj["prompt"]
            if not isinstance(ids, list) or not ids:
                return None
            try:
                return [int(t) for t in ids]
            except (ValueError, TypeError):
                return None

        def _disagg_plan(self, path, obj, ids
                         ) -> Optional[Tuple[Replica, Replica]]:
            """(prefill replica, decode replica) when the handoff is
            worth it, else None -> direct dispatch."""
            if path != "/generate" or not isinstance(obj, dict) \
                    or obj.get("stream") or ids is None:
                return None
            if len(ids) < state.page_size + 1:
                return None  # no full page to transfer
            if state.predicted_cost(ids) < state.disagg_threshold:
                return None
            dec_cands, _ = state.policy.plan(ids, role="decode")
            pre_cands, _ = state.policy.plan(ids, role="prefill")
            if not dec_cands or not pre_cands:
                return None
            dec = dec_cands[0]
            # a handoff to yourself is just a slower direct dispatch
            pre = next((r for r in pre_cands if r.rid != dec.rid), None)
            if pre is None:
                return None
            return pre, dec

        # -- the handoff ------------------------------------------------------

        @staticmethod
        def _transport_kind(e) -> str:
            """Classify a transport failure for the
            fleet_leg_failures_total{leg,kind} family."""
            import http.client
            reason = getattr(e, "reason", None)
            if isinstance(e, (socket.timeout, TimeoutError)) \
                    or isinstance(reason, (socket.timeout, TimeoutError)):
                return "timeout"
            if isinstance(e, http.client.IncompleteRead) \
                    or isinstance(reason, http.client.IncompleteRead):
                return "bad_body"  # died mid-body (truncated response)
            return "refused"  # refused / reset / garbled status line

        def _call(self, rep: Replica, method: str, path: str,
                  obj=None, timeout: Optional[float] = None,
                  request_id: Optional[str] = None, leg: str = "leg",
                  deadline_s: Optional[float] = None):
            """One control-plane HTTP call with pool feedback. Returns
            (status, parsed body) — status None on transport failure.
            `request_id` rides as X-Request-Id so the replica's tracer
            (and its kv-transfer error bodies) key the same distributed
            request the control plane is tracing. `leg` names the
            handoff leg for the classified
            fleet_leg_failures_total{leg,kind} accounting (timeout vs
            refused vs bad_status vs bad_body), which also feeds the
            pool's per-replica circuit breaker. `deadline_s` (absolute
            monotonic) caps the socket timeout at the remaining budget
            and forwards it as X-Deadline-Ms so the replica re-anchors
            the budget at its own arrival."""
            url = f"http://{rep.host}:{rep.port}{path}"
            data = json.dumps(obj).encode() if obj is not None else None
            headers = {"Content-Type": "application/json"}
            if request_id:
                headers["X-Request-Id"] = request_id
            tmo = timeout or state.read_timeout
            if deadline_s is not None:
                rem = deadline_s - time.monotonic()
                headers["X-Deadline-Ms"] = str(max(1, int(rem * 1e3)))
                tmo = min(tmo, max(1e-3, rem))
            if state.chaos is not None:
                from butterfly_tpu.fleet.chaos import ChaosIdent
                inj = state.chaos.decide(
                    ChaosIdent(rid=rep.rid, role=rep.role), path,
                    where="call")
                if inj is not None:
                    if inj.kind == "delay":
                        time.sleep(inj.delay_s)
                    else:
                        # every non-delay call-scope fault is "the leg
                        # never produced a usable response" — fail it
                        # through the SAME accounting a real refused
                        # connect takes (pool liveness, breaker, leg
                        # counter), so chaos exercises the real paths
                        err = f"chaos: injected {inj.kind}"
                        state.record_leg_failure(leg, "chaos")
                        state.pool.note_connect_failure(rep.rid, err)
                        state.pool.note_leg_failure(rep.rid, err)
                        return None, {"error": err}
            req = urllib.request.Request(
                url, data=data, method=method, headers=headers)
            state.pool.note_dispatch(rep.rid)
            try:
                with urllib.request.urlopen(req, timeout=tmo) as resp:
                    status, raw = resp.status, resp.read()
            except urllib.error.HTTPError as e:
                try:
                    body = json.loads(e.read() or b"{}")
                except (ValueError, OSError):
                    body = {}
                e.close()
                if e.code == 503:
                    state.pool.note_wedged(rep.rid, "503 during handoff")
                if e.code >= 500 and e.code != 504:
                    # 5xx = the replica failed the leg (504 is the
                    # request's OWN deadline verdict, not replica
                    # health — it must not trip the breaker)
                    state.record_leg_failure(leg, "bad_status")
                    state.pool.note_leg_failure(rep.rid, f"http {e.code}")
                else:
                    state.pool.note_leg_ok(rep.rid)
                return e.code, body
            except Exception as e:  # refused / reset / timeout
                kind = self._transport_kind(e)
                state.record_leg_failure(leg, kind)
                state.pool.note_connect_failure(rep.rid, str(e))
                state.pool.note_leg_failure(rep.rid, str(e))
                return None, {"error": str(e)}
            finally:
                state.pool.note_done(rep.rid)
            try:
                body = json.loads(raw or b"{}")
            except (ValueError, UnicodeDecodeError) as e:
                # a 200 whose body doesn't parse: the replica (or the
                # network) corrupted the leg — distinct failure kind
                state.record_leg_failure(leg, "bad_body")
                state.pool.note_leg_failure(rep.rid, f"bad body: {e}")
                return None, {"error": f"bad body: {e}"}
            state.pool.note_leg_ok(rep.rid)
            return status, body

        def _fallback(self, obj, ids, tid, t_arrive, reason,
                      request_id: str = "",
                      deadline_s: Optional[float] = None) -> None:
            """A handoff leg failed before any client byte: re-dispatch
            the ORIGINAL request direct (the decode replica recomputes
            the whole prompt — slower, never wrong). A spent deadline
            short-circuits to 504 instead: re-running the prompt for a
            client that already missed its budget is pure waste."""
            if deadline_s is not None and time.monotonic() >= deadline_s:
                self._deadline_504(tid, request_id, t_arrive, "fallback")
                return
            state.inc(state._c_fallback)
            state.tracer.event(tid, "fallback", reason=reason)
            body = json.dumps(obj).encode()
            self._restamp_deadline(deadline_s)
            t0 = time.monotonic()
            served = self._dispatch("/generate", body,
                                    *state.direct_plan(ids))
            state.tracer.event(tid, "direct_leg",
                               dur_s=time.monotonic() - t0,
                               replica=served,
                               status="ok" if served else "failed")
            state.tracer.event(tid, "finish", state="fallback",
                               total_s=time.monotonic() - t_arrive)

        def _disaggregate(self, obj: dict, ids: List[int],
                          pre: Replica, dec: Replica, tid: int,
                          request_id: str, t_arrive: float,
                          deadline_s: Optional[float] = None) -> None:
            t0 = t_arrive  # TTFT/total measure from client arrival
            state.inc(state._c_disagg)
            max_tokens = int(obj.get("max_tokens",
                                     obj.get("max_new_tokens", 64)))
            # 1. prefill leg: full prompt + first token on the prefill tier
            a_req = {"tokens": ids, "max_tokens": 1,
                     "request_id": request_id}
            for k in ("temperature", "stop_token", "priority"):
                if k in obj:
                    a_req[k] = obj[k]
            t_leg = time.monotonic()
            code, a = self._call(pre, "POST", "/generate", a_req,
                                 timeout=state.handoff_timeout,
                                 request_id=request_id, leg="prefill_leg",
                                 deadline_s=deadline_s)
            state.tracer.event(tid, "prefill_leg",
                               dur_s=time.monotonic() - t_leg,
                               replica=pre.rid,
                               status="ok" if code == 200 else f"{code}")
            if code == 504:
                # the replica's own deadline verdict: propagate, never
                # fall back — a re-prefill for a blown budget is waste
                self._deadline_504(tid, request_id, t_arrive,
                                   "prefill_leg", detail=a)
                return
            if code != 200 or not a.get("tokens"):
                self._fallback(obj, ids, tid, t_arrive,
                               f"prefill leg {code}",
                               request_id=request_id,
                               deadline_s=deadline_s)
                return
            ttft = time.monotonic() - t0
            state.observe(state._h_ttft, ttft)
            first = [int(t) for t in a["tokens"]]
            # 2. KV transfer: the prompt's full-page chain, A -> B.
            # Failures are absorbed — B prefills uncovered blocks itself.
            imported = 0
            hashes = [h.hex() for h in chain_block_hashes(ids,
                                                          state.page_size)]
            if hashes and not (deadline_s is not None
                               and time.monotonic() >= deadline_s):
                # transfer is an optimization: with a spent budget it
                # is simply skipped (the 504 verdict comes from the
                # decode leg below, which owns the terminal response)
                t_leg = time.monotonic()
                code, exp = self._call(
                    pre, "GET", "/kv/pages?hashes=" + ",".join(hashes),
                    timeout=state.handoff_timeout, request_id=request_id,
                    leg="kv_export", deadline_s=deadline_s)
                n_pages = len(exp.get("pages", ())) if code == 200 else 0
                state.tracer.event(
                    tid, "kv_export", dur_s=time.monotonic() - t_leg,
                    replica=pre.rid, pages=n_pages,
                    bytes=int(exp.get("bytes", 0)) if code == 200 else 0,
                    status="ok" if code == 200 else f"{code}")
                if code == 200:
                    state.add(state._c_xfer_hits, n_pages)
                    state.add(state._c_xfer_miss,
                              len(exp.get("missing", ())))
                    state.add(state._c_xfer_bytes,
                              int(exp.get("bytes", 0)))
                    if n_pages:
                        t_leg = time.monotonic()
                        code, imp = self._call(dec, "POST", "/kv/import",
                                               exp,
                                               timeout=state.handoff_timeout,
                                               request_id=request_id,
                                               leg="kv_import",
                                               deadline_s=deadline_s)
                        if code == 200:
                            # skipped = already cached on B (an earlier
                            # transfer or B's own traffic): warm either
                            # way, the handoff's purpose
                            imported = int(imp.get("imported", 0)) \
                                + int(imp.get("skipped", 0))
                            state.add(state._c_xfer_pages, imported)
                        state.tracer.event(
                            tid, "kv_import",
                            dur_s=time.monotonic() - t_leg,
                            replica=dec.rid, imported=imported,
                            status="ok" if code == 200 else f"{code}")
            state.note_seen(ids)
            meta = {"disaggregated": True, "prefill_replica": pre.rid,
                    "decode_replica": dec.rid, "request_id": request_id,
                    "kv_pages_imported": imported, "ttft_s": ttft}
            # 3. decode leg: prompt + first token, remaining budget.
            # Admission on B prefix-hits the imported pages and
            # prefills only the partial trailing block.
            if max_tokens <= 1 or a.get("stopped"):
                self._finish_disagg(t0, first, a.get("text", ""),
                                    a.get("stopped", False), meta, dec.rid,
                                    tid)
                return
            if deadline_s is not None and time.monotonic() >= deadline_s:
                # budget spent between prefill and decode: terminal 504
                # — the decode tier never sees (or seats) this request
                self._deadline_504(tid, request_id, t_arrive,
                                   "decode_leg")
                return
            b_req = {"tokens": ids + first, "max_tokens": max_tokens - 1,
                     "request_id": request_id}
            for k in ("temperature", "stop_token", "top_p", "top_k",
                      "priority"):
                if k in obj:
                    b_req[k] = obj[k]
            t_leg = time.monotonic()
            code, b = self._call(dec, "POST", "/generate", b_req,
                                 request_id=request_id, leg="decode_leg",
                                 deadline_s=deadline_s)
            state.tracer.event(tid, "decode_leg",
                               dur_s=time.monotonic() - t_leg,
                               replica=dec.rid,
                               tokens=len(b.get("tokens", ())),
                               status="ok" if code == 200 else f"{code}")
            if code == 504:
                self._deadline_504(tid, request_id, t_arrive,
                                   "decode_leg", detail=b)
                return
            if code != 200:
                self._fallback(obj, ids, tid, t_arrive,
                               f"decode leg {code}",
                               request_id=request_id,
                               deadline_s=deadline_s)
                return
            self._finish_disagg(
                t0, first + [int(t) for t in b.get("tokens", ())],
                a.get("text", "") + b.get("text", ""),
                b.get("stopped", False), meta, dec.rid, tid)

        def _finish_disagg(self, t0, tokens, text, stopped, meta,
                           rid, tid) -> None:
            state.count(rid, "ok")
            total = time.monotonic() - t0
            ttft = meta.get("ttft_s")
            itl_mean = ((total - ttft) / (len(tokens) - 1)
                        if ttft is not None and len(tokens) > 1 else None)
            verdicts = state.observe_slo(ttft, itl_mean)
            attrs = dict(verdicts)
            if itl_mean is not None:
                attrs["itl_mean_s"] = itl_mean
            state.tracer.event(tid, "finish", state="disaggregated",
                               tokens=len(tokens), total_s=total,
                               ttft_s=ttft, **attrs)
            self._json(200, {
                "tokens": tokens, "text": text, "stopped": stopped,
                "total_s": total, **meta, **verdicts,
            }, headers={"X-Routed-To": rid})

    return FleetHandler


def fleet_forever(backends: List[str], host: str = "0.0.0.0",
                  port: int = 8100, page_size: int = 16,
                  affinity_blocks: int = 4, saturate_after: int = 8,
                  probe_interval: float = 0.5, probe_timeout: float = 2.0,
                  dead_after: int = 3, read_timeout: float = 300.0,
                  disagg_threshold: int = 64,
                  slo_ttft_s: Optional[float] = None,
                  slo_itl_s: Optional[float] = None,
                  ready_event=None):
    """Blocking control-plane loop (`butterfly route --disaggregate`).
    Same shape as router.proxy.route_forever — the control plane IS the
    router, grown KV-aware."""
    import threading
    from http.server import ThreadingHTTPServer

    registry = MetricsRegistry()
    pool = ReplicaPool(backends, probe_interval=probe_interval,
                       probe_timeout=probe_timeout, dead_after=dead_after,
                       registry=registry, scrape_metrics=True)
    policy = PrefixAffinityPolicy(pool, page_size=page_size,
                                  affinity_blocks=affinity_blocks,
                                  saturate_after=saturate_after)
    state = ControlPlaneState(pool, policy, registry=registry,
                              read_timeout=read_timeout,
                              disagg_threshold=disagg_threshold,
                              slo_ttft_s=slo_ttft_s, slo_itl_s=slo_itl_s)
    pool.probe_all()   # one synchronous round: roles known at bind
    pool.start()

    class _Server(ThreadingHTTPServer):
        request_queue_size = 128

    httpd = _Server((host, port), make_fleet_handler(state))
    state.httpd = httpd
    if ready_event is not None:
        ready_event.set()
    snaps = pool.snapshot()
    n_pre = sum(1 for s in snaps if s["role"] in ("prefill", "both"))
    n_dec = sum(1 for s in snaps if s["role"] in ("decode", "both"))
    print(f"[butterfly] fleet control plane on {host}:{port}: "
          f"{len(snaps)} replicas ({n_pre} prefill-capable, "
          f"{n_dec} decode-capable), disagg threshold "
          f"{state.disagg_threshold} tokens", flush=True)
    try:
        httpd.serve_forever()
    finally:
        pool.stop()
        httpd.server_close()
    return 0
