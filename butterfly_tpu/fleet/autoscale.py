"""Closed-loop fleet autoscaler: grow/shrink the prefill and decode
tiers independently from scraped signal HISTORY.

The control loop sits on the control plane next to the router (it is
the consumer the per-replica scrape rings were built for): each step it
reads `pool.series_by_replica()` — the last ~4 minutes of every
replica's unlabeled gauges at scrape cadence — and compares each tier's
trailing per-replica mean of one signal (queue_depth by default)
against a high/low band. Ring history rather than instantaneous
samples is the whole point: a single scrape of queue_depth says nothing
(queues oscillate at batch cadence); a window mean says "this tier has
been saturated for N scrape intervals".

Scale-up goes through ``FleetHandle.spawn`` (shared-param-tree attach,
warm-before-join — a joining replica never serves a compile-cold
request), scale-down through ``FleetHandle.retire``
(drain-before-retire — no request is dropped across a shrink). Both
are injected as plain callables so unit tests drive decisions against
a fake pool without booting replicas.

Two guards shape the loop:

* **Shedding is the backpressure floor.** When a tier's replicas start
  returning 429s (the scheduler's predicted-TTFT admission shedding),
  the tier is under-provisioned *by definition* — the gauge band is
  bypassed and the tier scales up on the shed evidence alone. The
  autoscaler reads the ``shed_total`` counter deltas straight from the
  pool's parsed scrapes.
* **Scale-down hysteresis.** A shrink is only allowed once a full
  ``cooldown_down_s`` has passed since the tier's last scale action in
  EITHER direction. Without it the loop flaps: shrink drops capacity,
  queue depth rises, the next step grows again, forever paying the
  spawn warmup. Scale-up uses a much shorter cooldown — reacting
  slowly to overload costs SLO, reacting slowly to idleness only costs
  replica-seconds.

Every decision (and every refusal with a reason) lands in the control
plane's flight recorder, so `GET /debug/flightrecorder` shows scale
events interleaved with breaker opens and deadline 504s — the
"why did the fleet change shape at 14:03" audit trail.

stdlib-only.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

__all__ = ["TierPolicy", "Autoscaler"]


@dataclass
class TierPolicy:
    """Scaling policy for one fleet tier (one role)."""

    role: str                      # "prefill" | "decode" | "both"
    min_replicas: int = 1
    max_replicas: int = 4
    #: unlabeled replica gauge from the scrape rings (short name, e.g.
    #: "queue_depth", "active_requests", "slo_burn_rate")
    signal: str = "queue_depth"
    high: float = 4.0              # tier mean above -> scale up
    low: float = 0.5               # tier mean below -> scale down
    window: int = 3                # trailing ring samples averaged
    cooldown_up_s: float = 2.0     # min gap before another grow
    cooldown_down_s: float = 15.0  # hysteresis: quiet time before shrink

    def __post_init__(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"tier {self.role!r}: need 0 <= min <= max, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.low >= self.high:
            raise ValueError(
                f"tier {self.role!r}: low band {self.low} must sit below "
                f"high band {self.high} (the dead zone IS the hysteresis)")


@dataclass
class _Decision:
    """One evaluated step for one tier (kept for tests/benchmarks)."""
    t: float
    tier: str
    direction: Optional[str]       # "up" | "down" | None (held)
    reason: str
    value: Optional[float]
    n_before: int
    rid: Optional[str] = None


class Autoscaler:
    """The control loop. ``step()`` is synchronous and injectable-time
    (unit tests drive it sample by sample); ``start()`` runs it on a
    daemon thread at ``interval_s`` for live fleets."""

    def __init__(self, state, spawn: Callable, retire: Callable,
                 policies: List[TierPolicy], interval_s: float = 1.0):
        roles = [p.role for p in policies]
        if len(set(roles)) != len(roles):
            raise ValueError(f"duplicate tier policies: {roles}")
        self.state = state
        self.pool = state.pool
        self.spawn = spawn    # role -> handle-or-rid
        self.retire = retire  # rid -> bool
        self.policies = list(policies)
        self.interval_s = interval_s
        self._last_scale: Dict[str, float] = {}
        self._last_step_t: Optional[float] = None
        self._last_shed: Dict[str, float] = {}
        self.replica_seconds = 0.0  # integral of live replicas over time
        self.decisions: List[_Decision] = []
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = state.registry
        self._c_decisions = reg.counter_family(
            "fleet_autoscale_decisions_total",
            "Autoscaler scale actions taken, by tier and direction",
            ("tier", "direction"))
        self._c_held = reg.counter_family(
            "fleet_autoscale_held_total",
            "Scale actions wanted but refused (cooldown/bounds), by tier",
            ("tier",))
        self._c_shed_floor = reg.counter(
            "fleet_autoscale_shed_floor_total",
            "Scale-ups forced by replica admission shedding (429s) "
            "bypassing the signal band — the backpressure floor")
        self._c_errors = reg.counter(
            "fleet_autoscale_errors_total",
            "Spawn/retire attempts that raised (decision was logged, "
            "fleet shape unchanged)")
        self._g_tier = reg.gauge_family(
            "fleet_autoscale_replicas",
            "Current replicas per tier as the autoscaler sees them",
            ("tier",))
        self._g_repsec = reg.gauge(
            "fleet_autoscale_replica_seconds_total",
            "Integral of live replica count over wall time since the "
            "loop started — the cost side of the elasticity tradeoff")

    # -- signal reads --------------------------------------------------------

    def _tier_rids(self, role: str) -> List[str]:
        with self.pool._lock:
            return [rid for rid, r in self.pool.replicas.items()
                    if r.role == role]

    def _trailing_mean(self, samples: List[dict], signal: str,
                       window: int) -> Optional[float]:
        vals = [s["signals"][signal] for s in samples[-window:]
                if signal in s.get("signals", {})]
        if not vals:
            return None
        return sum(vals) / len(vals)

    def _tier_signal(self, rids: List[str], pol: TierPolicy,
                     series: Dict[str, List[dict]]) -> Optional[float]:
        """Mean over the tier's replicas of each replica's trailing
        window mean. Replicas with no ring data yet (just spawned, or
        scrapes failing) contribute nothing — a tier with NO data holds
        rather than guessing."""
        means = []
        for rid in rids:
            m = self._trailing_mean(series.get(rid, []), pol.signal,
                                    pol.window)
            if m is not None:
                means.append(m)
        if not means:
            return None
        return sum(means) / len(means)

    def _shed_delta(self, rids: List[str]) -> float:
        """New shed_total counts since the previous step across the
        tier, read from the pool's parsed scrapes (sheds are a labeled
        counter family, so they never appear in the gauge rings)."""
        by_rid = self.pool.metrics_by_replica()
        delta = 0.0
        for rid in rids:
            fam = (by_rid.get(rid) or {}).get("butterfly_shed_total")
            if not fam:
                continue
            total = sum(v for v in fam["samples"].values())
            prev = self._last_shed.get(rid, total)
            delta += max(0.0, total - prev)
            self._last_shed[rid] = total
        return delta

    # -- the loop ------------------------------------------------------------

    def step(self, now: Optional[float] = None) -> List[_Decision]:
        """Evaluate every tier once. Returns this step's decisions
        (direction None = held, with the reason)."""
        if now is None:
            now = time.monotonic()
        # replica-seconds integral: cost accounting for the acceptance
        # comparison against a static peak-provisioned fleet
        if self._last_step_t is not None and now > self._last_step_t:
            with self.pool._lock:
                n_live = len(self.pool.replicas)
            self.replica_seconds += n_live * (now - self._last_step_t)
            self._g_repsec.set(self.replica_seconds)
        self._last_step_t = now

        series = self.pool.series_by_replica()
        out: List[_Decision] = []
        for pol in self.policies:
            out.append(self._step_tier(pol, series, now))
        self.decisions.extend(out)
        del self.decisions[:-1024]
        return out

    def _step_tier(self, pol: TierPolicy, series: Dict[str, List[dict]],
                   now: float) -> _Decision:
        rids = self._tier_rids(pol.role)
        n = len(rids)
        self._g_tier.labels(pol.role).set(n)
        value = self._tier_signal(rids, pol, series)
        shed = self._shed_delta(rids)

        direction: Optional[str] = None
        reason = "in_band"
        if n < pol.min_replicas:
            direction, reason = "up", "below_min"
        elif n > pol.max_replicas:
            direction, reason = "down", "above_max"
        elif shed > 0 and n < pol.max_replicas:
            # backpressure floor: replicas 429ing means the signal band
            # is already academic — grow on the shed evidence alone
            direction, reason = "up", "shed_floor"
        elif value is not None and value > pol.high:
            if n < pol.max_replicas:
                direction, reason = "up", "signal_high"
            else:
                reason = "at_max"
        elif value is not None and value < pol.low:
            if n > pol.min_replicas:
                direction, reason = "down", "signal_low"
            else:
                reason = "at_min"
        elif value is None:
            reason = "no_data"

        last = self._last_scale.get(pol.role, float("-inf"))
        if direction == "up" and reason != "below_min" \
                and now - last < pol.cooldown_up_s:
            self._c_held.labels(pol.role).inc()
            return self._held(now, pol, "cooldown_up", value, n)
        if direction == "down":
            # scale-down hysteresis: a shrink needs a FULL quiet window
            # since the tier's last scale action in either direction,
            # or grow->shrink->grow flapping pays the warmup forever
            if now - last < pol.cooldown_down_s:
                self._c_held.labels(pol.role).inc()
                return self._held(now, pol, "cooldown_down", value, n)

        if direction is None:
            return _Decision(now, pol.role, None, reason, value, n)

        rid = None
        try:
            if direction == "up":
                h = self.spawn(pol.role)
                rid = getattr(h, "rid", h)
                if reason == "shed_floor":
                    self._c_shed_floor.inc()
            else:
                rid = self._pick_victim(rids, pol, series)
                self.retire(rid)
        except Exception as e:  # fleet shape unchanged; loop survives
            self._c_errors.inc()
            self.state.flightrec.note(
                "scale_error", tier=pol.role, direction=direction,
                reason=reason, error=f"{type(e).__name__}: {e}")
            return self._held(now, pol, "action_failed", value, n)

        self._last_scale[pol.role] = now
        self._c_decisions.labels(pol.role, direction).inc()
        self.state.flightrec.note(
            "scale", tier=pol.role, direction=direction, reason=reason,
            value=-1.0 if value is None else round(value, 4),
            n_before=n, n_after=n + (1 if direction == "up" else -1),
            rid=rid)
        return _Decision(now, pol.role, direction, reason, value, n,
                         rid=rid)

    def _held(self, now: float, pol: TierPolicy, why: str,
              value: Optional[float], n: int) -> _Decision:
        self.state.flightrec.note(
            "scale_held", tier=pol.role, reason=why,
            value=-1.0 if value is None else round(value, 4), n=n)
        return _Decision(now, pol.role, None, why, value, n)

    def _pick_victim(self, rids: List[str], pol: TierPolicy,
                     series: Dict[str, List[dict]]) -> str:
        """Least-loaded member: fewest router-tracked in-flight legs,
        then lowest trailing signal mean — retiring the busiest member
        would maximize the drain wait for no reason."""
        def load(rid: str):
            r = self.pool.get(rid)
            out = r.outstanding if r is not None else 0
            m = self._trailing_mean(series.get(rid, []), pol.signal,
                                    pol.window)
            return (out, m if m is not None else 0.0, rid)
        return min(rids, key=load)

    # -- daemon --------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_ev.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="butterfly-autoscale")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_ev.wait(self.interval_s):
            try:
                self.step()
            except Exception as e:  # never kill the loop from one step
                self._c_errors.inc()
                self.state.flightrec.note(
                    "scale_error", tier="*", direction="none",
                    reason="step_raised",
                    error=f"{type(e).__name__}: {e}")

    def stop(self) -> None:
        self._stop_ev.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- reporting -----------------------------------------------------------

    def stats(self) -> Dict:
        """Benchmark/acceptance summary: cost integral + action log."""
        acted = [d for d in self.decisions if d.direction is not None]
        return {
            "replica_seconds": round(self.replica_seconds, 3),
            "steps": len(self.decisions),
            "scale_ups": sum(1 for d in acted if d.direction == "up"),
            "scale_downs": sum(1 for d in acted if d.direction == "down"),
            "events": [
                {"t": d.t, "tier": d.tier, "direction": d.direction,
                 "reason": d.reason, "rid": d.rid,
                 "value": d.value} for d in acted],
        }
