"""Fleet control plane: disaggregated prefill/decode serving.

Grows the multi-replica router (butterfly_tpu/router/) into a KV-aware
control plane (the DistServe / Mooncake architecture): prefill-heavy
requests run on prefill-role replicas, their KV pages stream to a
decode-role replica by content hash (fleet/kvtransfer.py over the
prefix-cache page registry), and generation finishes there.

* kvtransfer.py   — chain-hash-addressed KV page export/import payloads
                    (the replica side of GET /kv/pages, POST /kv/import)
* controlplane.py — the routing tier: request classification, the
                    prefill -> transfer -> decode handoff, fleet-state
                    polling, GET /fleet/state
* harness.py      — in-process fleet topologies (`butterfly fleet
                    --topology 2p2d`, the soak tests, the fleet bench)
"""
from butterfly_tpu.fleet.kvtransfer import export_payload, import_payload

__all__ = ["export_payload", "import_payload"]
