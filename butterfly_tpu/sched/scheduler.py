"""Continuous-batching scheduler: admission, decode interleave, preemption.

Realizes the reference's planned "Scheduling System" layer
(/root/reference/CLAUDE.md:22 — "Workload distribution and synchronization
across compute nodes"; no implementation exists, SURVEY.md §0) for the
BASELINE.json configs[4] serving shape.

Host-side policy over the static-shape device programs in
engine/serving.py:

* tick() = [lazy drain — the OLDEST in-flight decode block only, and
  only when the in-flight queue is full] then [≤ prefill_chunk tokens
  of GROUP prefill work — waiting requests are gang-admitted, up to
  prefill_max_batch of them, and their next chunks run as batched
  [B, Tbucket] dispatches (engine.prefill_batch), bucketed by chunk
  length] then [ONE fused decode block of decode_steps_per_tick
  iterations for all active slots — a single jitted scan,
  engine._decode_scan — CHAINED on the previous block's
  device-resident carry]. Up to RuntimeConfig.inflight_blocks decode
  blocks stay in flight (dispatch-ahead): block t+1 is dispatched
  before block t is drained, so the tick's host section — admission,
  operand assembly, the stacked fetch itself — overlaps the device
  computing earlier blocks instead of idling it. A membership change
  (admission work, a finish surfacing at drain, preemption, cancel)
  forces a FULL drain barrier so host and device bookkeeping reconcile
  before the next dispatch. Speculative mode dispatches fused SPEC
  blocks through the same pipeline: drafts come from a device-resident
  token history, acceptance (with the rejection-sampling correction at
  temperature > 0) is computed inside the scan, and blocks chain on
  the (history, budgets) carry — no per-round barrier. Long prompts are
  split into prefill_chunk-sized pieces that continue the warm cache
  across ticks (partially-prefilled gang members carry over), so a
  max-length admission can never head-of-line-block decoding requests
  for more than one chunk, and a burst of arrivals prefills as a
  group instead of one prompt per tick.
* scheduler="static" disables interleaving: a whole batch is admitted
  (full prompts at once) only when the previous batch has fully drained —
  the classic throughput-oriented static-batching mode.
* Admission allocates pages for prompt+1; each decode step grows a slot's
  pages just-in-time. If the pool is exhausted, the youngest running
  request is PREEMPTED (pages freed, request requeued; its prompt +
  generated-so-far become the new prompt and are recomputed on
  readmission — vLLM-style recompute preemption).
* Per-request sampling: temperature is a per-slot device array;
  stop-token/max-tokens checks are host-side (the host sees every token
  anyway when streaming).
"""
from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from butterfly_tpu.cache.allocator import make_page_allocator
from butterfly_tpu.engine.serving import (
    ServingEngine, bucket_len, sample_batched)
from butterfly_tpu.obs.registry import (
    BATCH_BUCKETS, LATENCY_BUCKETS, TOKEN_BUCKETS, MetricsRegistry)
from butterfly_tpu.obs.ticklog import TICK_PHASES, TickLog

#: spec_accept_rate histogram buckets: acceptance fractions in [0, 1]
#: (upper bounds; the 1.0 bucket is the all-drafts-accepted round)
SPEC_ACCEPT_BUCKETS = (0.01, 0.125, 0.25, 0.375, 0.5,
                       0.625, 0.75, 0.875, 1.0)


def _device_ready(x) -> bool:
    """Non-blocking completion probe for a device array (jax.Array
    .is_ready — true once the async dispatch has materialized it). On a
    runtime without the probe, report not-ready: the device_bubble
    metric then reads a constant 0 (silently disabled) instead of
    claiming a bubble on every tick."""
    try:
        return bool(x.is_ready())
    except AttributeError:
        return False


@dataclass
class Request:
    id: int
    prompt: List[int]
    max_new_tokens: int = 128
    temperature: float = 0.0
    stop_token: int = -1
    # client-supplied passthrough id (X-Request-Id / body "request_id"):
    # appears verbatim in traces so client logs join server timelines
    client_id: Optional[str] = None
    # priority class: "interactive" sheds last and is preempted last;
    # "batch" is the first shed under predicted-TTFT pressure and the
    # preferred preemption victim under page pressure
    priority: str = "interactive"
    # absolute time.monotonic() deadline (None = none declared). The
    # scheduler scrubs expired waiters every tick and cancels expired
    # runners at the next drain barrier — an expired request never
    # occupies a decode slot past its budget.
    deadline_s: Optional[float] = None
    # per-request speculation opt-out (only meaningful when the server
    # runs with speculative_gamma > 0): False rides the spec block but
    # ignores its drafts — the slot emits one exact plain-decode sample
    # per verify round (speculative_accept spec_mask semantics)
    speculative: bool = True
    # where the deadline fired ("waiting" | "running"), for the 504 body
    expired_where: Optional[str] = None
    # runtime state
    output: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    state: str = "waiting"  # waiting | prefilling | running | finished | cancelled
    prefilled: int = 0      # prompt tokens already in the KV cache
    preemptions: int = 0
    t_arrive: float = field(default_factory=time.monotonic)
    # last time the request entered the waiting queue (submit or
    # preemption): the queue_wait_seconds histogram measures from here
    t_enqueued: float = field(default_factory=time.monotonic)
    # prefix-cache hit length at the LAST admission: prefill_tokens
    # histogram observes len(prompt) - this (only tokens actually run)
    cached_at_admit: int = 0
    t_first_token: Optional[float] = None
    t_last_token: Optional[float] = None
    t_finish: Optional[float] = None
    on_token: Optional[Callable[["Request", int], None]] = None
    on_finish: Optional[Callable[["Request"], None]] = None

    @property
    def done(self) -> bool:
        return self.state in ("finished", "cancelled", "expired")

    @property
    def all_tokens(self) -> List[int]:
        """Prompt + generated-so-far: what a (re)prefill must cover."""
        return self.prompt + self.output

    @property
    def ttft(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_arrive


class Scheduler:
    """Continuous batching over a ServingEngine."""

    def __init__(self, engine: ServingEngine, seed: int = 0,
                 tracer=None, registry: Optional[MetricsRegistry] = None,
                 slo_ttft_s: Optional[float] = None,
                 slo_itl_s: Optional[float] = None,
                 flightrec=None, timeseries=None):
        self.engine = engine
        # Anomaly flight recorder (obs/ticklog.py FlightRecorder),
        # opt-in like the tracer: None keeps every call site a single
        # attribute-is-None check. When on, the scheduler notes
        # admission/preempt/shed/expiry/barrier/flush events into its
        # bounded ring and polls the trigger predicates once per tick.
        self.flightrec = flightrec
        # Periodic signal-history recorder (obs/timeseries.py
        # SignalRecorder), opt-in with the same None contract: when
        # off, the per-tick cost is one attribute-is-None check; when
        # on, _record_tick asks due() (one monotonic compare) and
        # samples the gauge/rate signal set at the recorder's interval.
        # It lives on the scheduler — not the server — so bench runs
        # record trajectories without an HTTP surface.
        self.timeseries = timeseries
        # Tracing is opt-in: trace=None keeps every hot-path call site a
        # single None check (obs/trace.py overhead contract). When on,
        # the engine shares the tracer for dispatch-level events.
        self.trace = tracer
        if tracer is not None and hasattr(engine, "tracer"):
            engine.tracer = tracer
        rt = engine.runtime
        if rt.scheduler not in ("continuous", "static"):
            raise ValueError(f"unknown scheduler {rt.scheduler!r}: "
                             "expected 'continuous' or 'static'")
        max_pages = engine.cache.page_table.shape[1]
        if rt.prefix_caching:
            from butterfly_tpu.cache.prefix import PrefixCachingAllocator
            self.alloc = PrefixCachingAllocator(
                engine.cache.num_pages - 1, engine.cache.page_size, max_pages)
        else:
            self.alloc = make_page_allocator(engine.cache.num_pages - 1,
                                             engine.cache.page_size, max_pages,
                                             num_slots=engine.num_slots)
        self.waiting: Deque[Request] = deque()
        self.running: List[Request] = []
        # Mixed dispatch (ISSUE 18): prefill chunks and decode/spec
        # tokens ride ONE fused block per tick (engine._mixed_scan and
        # twins) — admission becomes a host-side carry edit between
        # dispatches (_seed_mixed_slot) instead of a drain barrier +
        # separate prefill dispatch, retiring the admission barrier
        # cause as a class. Continuous scheduler only; stateful draft
        # sources fall back to the alternating path (their admission
        # reseed hook needs the barrier this mode deletes).
        self._mixed_mode = (rt.scheduler == "continuous"
                            and engine.mixed_dispatch_ready)
        # visibility (ISSUE 19 satellite): mixed dispatch was ASKED
        # for but the engine gated it back to the alternating path
        # (stateful draft source, or tree speculation — neither has a
        # fused mixed program). PR 18 made that fallback silent; the
        # reason string rides metrics() and the counter below makes
        # the gating countable in any scrape.
        self._mixed_fallback_reason = engine.mixed_fallback_reason \
            if rt.scheduler == "continuous" else None
        # per-step chunk width C: under spec the verify shape pins it
        # to gamma+1; otherwise the inline budget (clamped by the tick
        # chunk budget) IS the width — one prefilling slot chews C
        # tokens per scan step
        self._mixed_chunk = (rt.speculative_gamma + 1) if rt.speculative_gamma > 0 \
            else max(1, min(rt.prefill_inline_budget, rt.prefill_chunk))
        # concurrent-prefill cap — THE ITL-tail knob: at most this many
        # slots may be in prefill phase at once, so a scan step never
        # chews more than ~prefill_inline_budget prompt tokens while
        # decode slots wait on it
        self._mixed_max_pf = max(1, rt.prefill_inline_budget // self._mixed_chunk)
        # mixed-dispatch device carries: the per-slot chunk cursor
        # (DONATED to every mixed block, rebound from its result —
        # BTF002 contract) and, non-spec, the prompt-buffer rows the
        # prefill lanes read (under spec the token-history carry
        # doubles as the buffer). _plen_host is the per-slot prompt
        # length operand (host-owned; 0 marks a slot decode-phase).
        self._cursor_dev = None
        self._pbuf_dev = None
        self._plen_host = np.zeros((engine.num_slots,), np.int32)
        # prompt tokens advanced INSIDE fused mixed blocks (the work
        # the retired admission barrier used to serialize) — the bench
        # key mixed_dispatch_prefill_tokens_inline
        self._inline_pf_tokens = 0
        # The prefill GROUP: requests admitted to slots whose prompts are
        # not yet fully in the KV cache. Each tick their next chunks are
        # packed under the prefill_chunk token budget and dispatched as
        # batched [B, Tbucket] prefills (engine.prefill_batch);
        # partially-prefilled members carry over to the next tick. This
        # replaces the old single `_prefilling` request — a burst of
        # arrivals no longer serializes one [1, Tbucket] dispatch per
        # prompt while decode slots sit idle.
        self._prefill_group: List[Request] = []
        # Long-prompt seq-parallel lane (ISSUE 20): prompts longer than
        # RuntimeConfig.seq_parallel_threshold prefill through chunked
        # seq-parallel dispatches (engine.sp_prefill_chunk — ring
        # attention over the mesh's seq axis, K/V landing in the
        # ordinary page pool) and then decode as normal paged slots. At
        # most ONE request occupies the lane: each chunk dispatch
        # already spans every seq-axis device, so a second concurrent
        # long prefill would only queue behind the first's dispatches.
        self._sp_group: List[Request] = []
        self._sp_enabled = (rt.seq_parallel_threshold > 0
                            and engine.supports_seq_parallel)
        if rt.seq_parallel_threshold > 0 and not self._sp_enabled:
            import warnings
            warnings.warn(
                "seq_parallel_threshold set but the engine cannot "
                "seq-parallel (needs a mesh with seq > 1 and stage == "
                "1); long prompts take the single-device chunk path",
                RuntimeWarning, stacklevel=2)
        # tokens per seq-parallel dispatch: each shard chews about a
        # prefill_chunk worth of work, so one lane dispatch costs a
        # tick roughly what a dense prefill round does
        N = engine.sp_degree
        spc = rt.seq_parallel_chunk or N * max(1, rt.prefill_chunk)
        self._sp_chunk = -(-spc // max(1, N)) * max(1, N)
        self.slots: List[Optional[Request]] = [None] * engine.num_slots
        self._ids = itertools.count()
        self._key = jax.random.PRNGKey(seed)
        self._next_tokens = np.zeros((engine.num_slots,), np.int32)
        # In-flight fused blocks, tagged tuples in dispatch order:
        #   ("decode", final [S] carry, block [k, S], k, snapshot, t)
        #   ("spec",   hist_len [S],   (toks [R, S, C], valid
        #              [R, S, C]), R rounds, snapshot, t)
        #   ("mixed",  final [S], (block [k, S], valid [k, S]), k,
        #              snapshot, t, pf_done slots, emit_vec [S])
        #   ("mixed_spec", hist_len [S], (toks, valid) [R, S, C], R,
        #              snapshot, t, pf_done slots, None)
        # where snapshot maps slot -> (request, generation); mixed
        # entries additionally carry the slots whose prefill completed
        # inside the block (drain-time state transitions) and, plain
        # mixed, the host-simulated per-slot emission counts the next
        # dispatch's budget look-ahead subtracts. Each tick
        # dispatches ONE jitted scan (engine.decode_block_async or
        # engine.spec_block_async) chained on the previous block's
        # device-resident carry, and up to
        # RuntimeConfig.inflight_blocks of them stay undrained
        # (dispatch-ahead): the host fetches only the OLDEST block when
        # the queue fills, so its drain + the next tick's scheduling
        # run while the device computes the newer blocks. This is what
        # closes the serving loop toward the isolated-decode ceiling
        # (BENCH_r05: 320 serving vs 6,988 isolated tok/s/chip) and
        # what makes it survive high host<->device latency (the dev
        # tunnel here has ~100 ms dispatch+fetch RTT).
        self._inflight: List[tuple] = []
        # Batch-membership epoch: bumped whenever the running set, the
        # pending-first set, or any runner's drained output changes
        # (admission completing, finish, preemption, any drain).
        # _decode_block caches its host operand assembly — the
        # active/temps/stops/base-budget arrays and the slot snapshot —
        # keyed on it, so back-to-back blocks over an unchanged batch
        # skip the per-slot Python rebuild and the np.asarray churn.
        self._epoch = 0
        self._operands_epoch = -1
        self._operands: Optional[tuple] = None
        # device_bubble_seconds observation points, set at tick start:
        # host-section start time and whether the device was ALREADY
        # idle then (the newest in-flight block's carry ready before
        # any host work ran — exactly the gap dispatch-ahead exists to
        # close). _decode_block observes the gap at dispatch.
        self._t_host0 = 0.0
        self._idle_at_host0 = False
        self._had_inflight_at_host0 = False
        # First tokens sampled on-device at admission, not yet fetched:
        # [(req, generation=req.preemptions, slot, device scalar)].
        # Fetched with the same stacked drain (a per-admission host
        # fetch would pay the full dispatch+fetch RTT per request).
        self._pending_first: List[tuple] = []
        # Membership index over _pending_first, keyed (request id,
        # preemptions) and refreshed at drain time: _decode_block's
        # budget computation and _written ask "does req have an
        # undrained first token?" per runner — a set lookup instead of
        # the old O(running x pending) linear scan.
        self._pending_first_keys: set = set()
        # Device twin of _next_tokens: the decode chain's input vector.
        # Admissions write their first token into it with a device-side
        # .at[].set, so dispatching never needs the host values.
        self._next_dev = None
        # Speculative-mode device carries (allocated only with
        # speculative_gamma > 0): the per-slot token history
        # [S, cache.max_seq] + live lengths the on-device drafter reads
        # (admissions write their prompt + first token in; spec blocks
        # append their own emissions in-scan), and the remaining-budget
        # vector the chained dispatches thread through
        # (None = rebuild from host state at the next dispatch — set at
        # every full drain barrier, when the host again knows every
        # emitted token).
        self._spec_mode = rt.speculative_gamma > 0
        self._hist_dev = None
        self._hist_len_dev = None
        self._spec_rem = None
        if self._spec_mode:
            H = engine.cache.max_seq
            self._hist_dev = jnp.zeros((engine.num_slots, H), jnp.int32)
            self._hist_len_dev = jnp.zeros((engine.num_slots,), jnp.int32)
        # Typed instruments (obs/registry.py) replace the old ad-hoc
        # Dict[str, float]: counters for the monotonic totals, fixed-
        # bucket histograms for the latency/size distributions /metrics
        # exposes as real _bucket/_sum/_count series. metrics() still
        # returns the legacy flat dict, assembled from the registry.
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        reg = self.registry
        self._c_requests = reg.counter(
            "requests_total", "Requests submitted")
        self._c_finished = reg.counter(
            "requests_finished", "Requests completed")
        self._c_tokens = reg.counter(
            "tokens_generated_total",
            "Tokens generated across all requests")
        self._c_preempt = reg.counter(
            "preemptions_total",
            "Recompute preemptions under page pressure")
        self._c_spec_fwd = reg.counter(
            "spec_forwards_total",
            "Speculative verify forwards that did work (spec-block "
            "rounds with at least one live slot)")
        self._c_spec_acc = reg.counter(
            "spec_drafts_accepted_total",
            "Draft tokens accepted by speculative verify")
        self._c_spec_tok = reg.counter(
            "spec_block_tokens_total",
            "Tokens emitted from speculative verify blocks (accepted "
            "drafts + corrections/bonus samples); divided by "
            "spec_forwards_total this is tokens/forward — the number "
            "speculation exists to push past 1")
        self._c_spec_mixed_fb = reg.counter(
            "spec_mixed_fallback_total",
            "Mixed dispatch requested but gated back to the "
            "alternating path at engine construction (stateful draft "
            "source needs the admission barrier; tree speculation has "
            "no fused mixed program) — nonzero means the "
            "mixed_dispatch flag is silently not in effect")
        if self._mixed_fallback_reason is not None:
            self._c_spec_mixed_fb.inc()
        self._h_accept = reg.histogram(
            "spec_accept_rate",
            "Per-slot-round draft acceptance fraction (accepted / "
            "gamma) over emitted rounds of speculating requests — 0 "
            "means every round paid a full verify for one token",
            SPEC_ACCEPT_BUCKETS)
        # Barrier-cause accounting (ISSUE 15): the single counter grew
        # a {cause} label so the bench can say WHICH membership-change
        # class costs the pipeline. The unlabeled sum survives as the
        # metrics()["drain_barriers_total"] compat key (and as the sum
        # of the labeled children in the exposition).
        self._c_barriers = reg.counter_family(
            "drain_barriers_total",
            "FULL drain barriers (every in-flight block fetched, "
            "pipeline restarts cold), by membership-change cause "
            "(admission, finish, page_pressure, cancel, spec, idle, "
            "expired, flush). Compare the sum with spec_forwards_total "
            "/ tick count: a healthy pipeline drains lazily and "
            "barriers only on membership changes, never once per "
            "decode or spec round", ("cause",))
        self._h_ttft = reg.histogram(
            "ttft_seconds",
            "Time to first token (submit -> first token drained)",
            LATENCY_BUCKETS)
        self._h_itl_mean = reg.histogram(
            "itl_req_mean_seconds",
            "Per-finished-request MEAN inter-token gap — the effective "
            "streaming rate a client experiences", LATENCY_BUCKETS)
        self._h_queue_wait = reg.histogram(
            "queue_wait_seconds",
            "Wait from submit (or preemption) to slot admission",
            LATENCY_BUCKETS)
        self._h_batch = reg.histogram(
            "batch_size", "Decoding slots active per scheduler tick",
            BATCH_BUCKETS)
        self._h_prefill_tokens = reg.histogram(
            "prefill_tokens",
            "Prompt tokens prefilled per admission (prefix-cache hits "
            "excluded)", TOKEN_BUCKETS)
        self._c_sp_tokens = reg.counter(
            "seq_parallel_prefill_tokens_total",
            "Prompt tokens prefilled through the long-prompt "
            "seq-parallel lane (chunked ring-attention dispatches; "
            "zero when seq_parallel_threshold is off or no prompt "
            "exceeded it)")
        self._h_prefill_batch = reg.histogram(
            "prefill_batch_size",
            "Requests packed into one batched [B, Tbucket] prefill "
            "dispatch (group admission; 1 = a lone member in its "
            "chunk-length bucket)", BATCH_BUCKETS)
        self._h_decode_block = reg.histogram(
            "decode_block_seconds",
            "Fused decode block in-flight residency: dispatch to "
            "stacked drain (covers decode_steps_per_tick device steps "
            "plus, under dispatch-ahead, the ticks the block waited "
            "undrained while newer blocks ran)", LATENCY_BUCKETS)
        self._h_bubble = reg.histogram(
            "device_bubble_seconds",
            "Device idle gap per dispatched decode block: 0 when the "
            "newest in-flight block was still running as the tick's "
            "host section began; otherwise the (lower-bound) time the "
            "idle device waited for the next dispatch",
            LATENCY_BUCKETS)
        self._g_inflight = reg.gauge(
            "inflight_depth",
            "Decode blocks in flight (dispatched, not yet drained) at "
            "the end of the last scheduler tick")
        # Write-combined KV window (RuntimeConfig.kv_write_combine):
        # every drain flushes the staged window into the page pool with
        # one scatter per pool tensor, BEFORE any finish registers or
        # reclaims pages. The histogram times the host-side flush
        # dispatch section (on an async backend the device cost shows
        # up in decode_block_seconds instead); the counter rides the
        # drain's stacked fetch, so it costs no extra sync.
        self._h_kv_flush = reg.histogram(
            "kv_flush_seconds",
            "Host wall time of the write-combined KV window flush "
            "dispatch at a drain (kv_write_combine; one pool scatter "
            "per drain instead of one per token per layer)",
            LATENCY_BUCKETS)
        self._c_kv_flushed = reg.counter(
            "kv_window_tokens_flushed_total",
            "Staged K/V tokens flushed from the write-combined decode "
            "window into the page pool (kv_write_combine); tokens "
            "whose requests died before a flush are dropped, not "
            "counted")
        self._kv_flushes: Deque[float] = deque(maxlen=4096)
        # Host-RAM KV tier (ISSUE 17, cache/hosttier.py): prefix-cache
        # eviction demotes page bytes to host DRAM (optionally spilling
        # to disk) instead of dropping them, and admission's prefix
        # walk revives them on a hit — the evict/revive hooks installed
        # on the allocator here are the only device-touching halves
        # (read_pages on evict, write_pages on revive); the tier itself
        # is pure host state. Off (None) unless prefix caching is on
        # AND a tier budget is declared.
        self.host_tier = None
        self._g_tier_hit = None
        self._tier_restores: Deque[float] = deque(maxlen=4096)
        if rt.prefix_caching and (rt.host_kv_tier_mb or 0) > 0:
            from butterfly_tpu.cache.hosttier import HostKVTier
            self.host_tier = HostKVTier(
                int(rt.host_kv_tier_mb * 1024 * 1024),
                spill_dir=rt.host_kv_tier_dir)
            self.alloc.on_evict = self._tier_save
            self.alloc.reviver = self._tier_revive
            self._c_tier_saved = reg.counter(
                "kv_tier_pages_saved_total",
                "KV pages demoted to the host tier at prefix-cache "
                "eviction (read_pages -> host DRAM) instead of dropped")
            self._c_tier_restored = reg.counter(
                "kv_tier_pages_restored_total",
                "KV pages revived from the host tier on a prefix hit "
                "(import_page + write_pages) — prefill work the tier "
                "saved")
            self._c_tier_miss = reg.counter(
                "kv_tier_misses_total",
                "Prefix-walk registry misses the host tier could not "
                "serve either (the chain was never demoted, or aged "
                "out of the tier's budget)")
            self._h_tier_restore = reg.histogram(
                "kv_tier_restore_seconds",
                "Host wall time to revive one page from the host tier "
                "(tier lookup + import_page + the device scatter)",
                LATENCY_BUCKETS)
            self._g_tier_hit = reg.gauge(
                "kv_tier_hit_rate",
                "Fraction of host-tier lookups served (restores / "
                "(restores + misses), all paths including export) — "
                "the tier-effectiveness signal dashboards sparkline")
        # SLO attainment (ISSUE 7): declared objectives make latency a
        # pass/fail measurement per request instead of a percentile to
        # eyeball. None = no objective declared: zero accounting runs
        # (the counters exist but never increment).
        self.slo_ttft_s = slo_ttft_s
        self.slo_itl_s = slo_itl_s
        self._c_slo_ttft_ok = reg.counter(
            "slo_ttft_ok_total",
            "First tokens delivered within the declared TTFT objective "
            "(--slo-ttft-ms)")
        self._c_slo_itl_ok = reg.counter(
            "slo_itl_ok_total",
            "Finished requests whose mean inter-token gap met the "
            "declared ITL objective (--slo-itl-ms)")
        self._c_slo_viol = reg.counter_family(
            "slo_violations_total",
            "Requests that missed a declared latency objective, by "
            "objective kind", ("kind",))
        self._g_slo_burn = reg.gauge(
            "slo_burn_rate",
            "Fraction of the last 256 finished requests that violated "
            "ANY declared objective (0 = meeting SLO, 1 = burning the "
            "whole error budget) — the rolling signal SLO-aware "
            "admission and autoscaling read")
        # Overload protection (ISSUE 8): deadline expiry + SLO-aware
        # admission shedding. Shedding activates only with a declared
        # TTFT objective AND observed latency evidence — a cold server
        # never sheds blind.
        self._c_deadline = reg.counter_family(
            "deadline_expired_total",
            "Requests that blew their declared deadline (deadline_ms / "
            "X-Deadline-Ms), by where they died: scrubbed from the "
            "waiting queue, or cancelled out of a decode slot",
            ("where",))
        self._c_shed = reg.counter_family(
            "shed_total",
            "Requests shed at admission (429) because predicted TTFT "
            "busts the declared --slo-ttft-ms, by priority class "
            "(batch sheds at the objective, interactive at "
            "interactive_slack x it)", ("priority",))
        # interactive requests tolerate this multiple of the TTFT
        # objective before shedding — batch is always shed first
        self.interactive_slack = 2.0
        # rolling attainment window backing the burn-rate gauge
        self._slo_window: Deque[float] = deque(maxlen=256)
        # latency reservoirs: both bounded to the same recent window so
        # the two adjacent metrics share time-horizon semantics (and a
        # long-lived server doesn't leak one float per request forever)
        self._ttfts: Deque[float] = deque(maxlen=4096)
        # inter-token gaps (seconds), bounded reservoir of the most
        # recent gaps across all requests — the latency a decoding
        # request experiences when admissions interleave (the quantity
        # chunked prefill exists to bound). With pipelined dispatch,
        # tokens surface in per-tick bursts, so raw gap percentiles
        # bimodalize (p50 ~ 0, p95 ~ tick); _itl_means tracks each
        # finished request's MEAN gap (t_last - t_first)/(n - 1) — the
        # effective per-token rate a streaming client experiences.
        self._itls: Deque[float] = deque(maxlen=4096)
        self._itl_means: Deque[float] = deque(maxlen=4096)
        # per-dispatch device-bubble samples (seconds; 0 = the pipeline
        # kept the device busy through the host section) for the
        # metrics() percentile keys bench.py reports
        self._bubbles: Deque[float] = deque(maxlen=4096)
        # -- tick anatomy (ISSUE 15) -----------------------------------------
        # Per-tick phase attribution: tick() zeroes the accumulator,
        # the structural sections add their exclusive time.monotonic()
        # deltas (host->host arithmetic only — the timers themselves
        # must never sync, BTF003 covers these paths), and the record
        # lands in the bounded timeline ring GET /debug/ticks serves.
        self.ticklog = TickLog(capacity=512)
        self._tick_phases: Dict[str, float] = {p: 0.0 for p in TICK_PHASES}
        self._tick_causes: List[str] = []
        # stacked-fetch device wait within this tick's drains: feeds
        # the host/device split (tick_host_frac / tick_device_frac) —
        # the fetch is the one tick section that blocks on the device
        self._tick_fetch = 0.0
        self._t_host_total = 0.0
        self._t_device_total = 0.0
        # per-phase histograms in the registry: real _bucket series per
        # structural phase, so dashboards see distributions, not means
        self._h_phase = {
            p: reg.histogram(
                f"tick_phase_{p}_seconds",
                f"Host wall time of the '{p}' tick phase per tick "
                "(docs/serving.md tick-pipeline vocabulary)",
                LATENCY_BUCKETS)
            for p in TICK_PHASES}

    def _phase_add(self, name: str, dt: float) -> None:
        """Accumulate one phase section's exclusive wall time into the
        current tick's record (plain dict arithmetic — never a sync)."""
        self._tick_phases[name] += dt

    # -- public API ---------------------------------------------------------

    def submit(self, prompt: List[int], max_new_tokens: int = 128,
               temperature: float = 0.0, stop_token: int = -1,
               on_token=None, on_finish=None,
               request_id: Optional[str] = None,
               priority: str = "interactive",
               deadline_s: Optional[float] = None,
               speculative: bool = True) -> Request:
        # Reject what can never fit: a request that exceeds the per-seq
        # page limit or the whole pool would self-preempt forever.
        worst = -(-(len(prompt) + max_new_tokens) // self.alloc.page_size)
        if worst > self.alloc.max_pages_per_seq or worst > self.alloc.num_pages:
            raise ValueError(
                f"request needs {worst} KV pages (prompt {len(prompt)} + "
                f"max_new {max_new_tokens}) but the limit is "
                f"{min(self.alloc.max_pages_per_seq, self.alloc.num_pages)}")
        if priority not in ("interactive", "batch"):
            raise ValueError(f"unknown priority {priority!r}: expected "
                             "'interactive' or 'batch'")
        req = Request(id=next(self._ids), prompt=list(prompt),
                      max_new_tokens=max_new_tokens, temperature=temperature,
                      stop_token=stop_token, client_id=request_id,
                      priority=priority, deadline_s=deadline_s,
                      speculative=bool(speculative),
                      on_token=on_token, on_finish=on_finish)
        self.waiting.append(req)
        self._c_requests.inc()
        if self.trace is not None:
            self.trace.begin_request(req.id, request_id=request_id,
                                     prompt_len=len(prompt),
                                     max_new_tokens=max_new_tokens)
        return req

    # -- overload protection (ISSUE 8) --------------------------------------

    def predict_ttft(self, prompt_len: int) -> Optional[float]:
        """Admission-time TTFT prediction for a hypothetical new
        arrival: the prefill backlog ahead of it (waiting prompts +
        unfinished prefill-group work + its own prompt) in
        prefill_chunk-budget rounds, plus one round per waiter ahead
        (slot contention), each round costed at the rolling
        per-request mean ITL — every chunk round shares a tick with a
        decode block, so the recent inter-token gap IS the tick cost a
        queued request pays. Returns None without latency evidence
        (cold server: never predict, never shed blind). Deliberately
        cheap — a misprediction costs one early 429 or one late
        admission, never correctness."""
        window = self._itl_means or self._itls
        if not window:
            return None
        tick_s = sum(window) / len(window)
        chunk = max(1, self.engine.runtime.prefill_chunk)
        backlog = prompt_len
        backlog += sum(len(r.all_tokens) - r.prefilled
                       for r in self._prefill_group)
        # seq-parallel lane work is shared N ways across the mesh
        backlog += sum(len(r.all_tokens) - r.prefilled
                       for r in self._sp_group) \
            // max(1, self.engine.sp_degree)
        backlog += sum(len(r.all_tokens) for r in self.waiting)
        rounds = -(-backlog // chunk) + len(self.waiting)
        return rounds * tick_s

    def shed_decision(self, prompt_len: int,
                      priority: str = "interactive") -> Optional[float]:
        """SLO-aware admission: seconds to advertise as Retry-After
        when the request should be SHED (predicted TTFT busts the
        declared objective), or None to admit. Batch sheds at the
        objective; interactive tolerates interactive_slack x it, so
        under rising load batch traffic is always turned away first.
        No declared --slo-ttft-ms = no shedding, ever."""
        if self.slo_ttft_s is None:
            return None
        pred = self.predict_ttft(prompt_len)
        if pred is None:
            return None
        limit = self.slo_ttft_s * (self.interactive_slack
                                   if priority == "interactive" else 1.0)
        if pred <= limit:
            return None
        self._c_shed.labels(priority).inc()
        if self.flightrec is not None:
            self.flightrec.note("shed", priority=priority,
                                predicted_ttft_s=pred, limit_s=limit)
        # how long until enough backlog drains that the prediction
        # would pass — the honest Retry-After, not a constant
        return max(1.0, pred - limit)

    def _expire_due(self) -> None:
        """Deadline scrub, run at every tick start. Expired waiters
        drop straight out of the queue (they never cost a prefill);
        expired runners force a FULL drain barrier first — their pages
        must not be reclaimed under an in-flight block's writes — then
        leave their decode slot. Either way the request finishes
        state="expired" and its waiter is answered (the server turns
        that into the 504)."""
        now = time.monotonic()
        for req in [r for r in self.waiting
                    if r.deadline_s is not None and now >= r.deadline_s]:
            self.waiting.remove(req)
            self._expire(req, "waiting")
        live = [r for r in self._all_live
                if r.deadline_s is not None and now >= r.deadline_s]
        if live:
            self._drain_inflight("expired")
            for req in live:
                if not req.done:  # the drain may have finished it
                    self._expire(req, "running")

    def _expire(self, req: Request, where: str) -> None:
        req.expired_where = where
        self._c_deadline.labels(where).inc()
        if self.flightrec is not None:
            self.flightrec.note("deadline_504", id=req.id, where=where,
                                tokens=len(req.output))
        self._finish(req, state="expired")

    def cancel(self, req: Request) -> None:
        """Abort a request (e.g. client disconnect): frees slot + pages.

        With decode blocks in flight a FULL drain barrier runs first:
        the blocks were dispatched with this request's slot live, and
        its pages must not be reclaimed (and possibly handed to a later
        admission) while device writes to them are still outstanding."""
        if req.done:
            return
        if req.slot is not None and (self._inflight or self._pending_first):
            self._drain_inflight("cancel")
            if req.done:
                return  # the drain surfaced a natural finish
        if req in self.waiting:
            self.waiting.remove(req)
        self._finish(req, state="cancelled")

    @property
    def _all_live(self) -> List[Request]:
        return (list(self.running) + list(self._prefill_group)
                + list(self._sp_group))

    def unfinished_requests(self) -> List[Request]:
        """Every request that would be lost in a crash: running,
        mid-chunked-prefill, and waiting — the set a serving snapshot
        (ckpt.sharded.save_serving_snapshot) must persist."""
        return self._all_live + list(self.waiting)

    def abort_all(self) -> None:
        """Wedge-path drain: host-only bookkeeping, NO device calls (the
        device may be the thing that's broken). Every waiter's on_finish
        fires; slots/pages are reclaimed in host state only."""
        # never block on a possibly-wedged device
        self._inflight = []
        self._pending_first = []
        self._pending_first_keys.clear()
        self._spec_rem = None
        # staged-but-unflushed window K/V is DROPPED, not flushed (no
        # device calls here): every owning request is being cancelled,
        # and dropping resets the staged count so a later flush can
        # never scatter stale entries into reclaimed pages
        self.engine.drop_kv_window()
        self._plen_host[:] = 0  # mixed carries: every slot decode-phase
        self._epoch += 1  # cached decode operands are now stale
        for req in self.unfinished_requests():
            req.state = "cancelled"
            req.t_finish = time.monotonic()
            if self.trace is not None:
                self.trace.event(req.id, "finish", state="cancelled",
                                 reason="abort_all",
                                 tokens=len(req.output))
            if req.slot is not None:
                self.alloc.release(req.slot)
                self.slots[req.slot] = None
                req.slot = None
            if req.on_finish is not None:
                try:
                    req.on_finish(req)
                except Exception:
                    pass
        self.running.clear()
        self.waiting.clear()
        self._prefill_group.clear()
        self._sp_group.clear()

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.running or self._prefill_group
                    or self._sp_group)

    def run_until_done(self, max_ticks: int = 100000) -> None:
        for _ in range(max_ticks):
            if not self.has_work:
                return
            self.tick()
        raise RuntimeError("scheduler did not drain")

    def tick(self) -> int:
        """One scheduling round: lazy drain, bounded prefill work, then
        a dispatch-ahead decode block.

        Continuous mode keeps up to `RuntimeConfig.inflight_blocks`
        fused decode blocks in flight: block t+1 chains on block t's
        device-resident carry BEFORE t is drained, so this tick's host
        section — drain bookkeeping, admission, operand assembly —
        overlaps the device computing earlier blocks instead of idling
        it (the BENCH_r05 serving gap). Draining is lazy: only the
        oldest block is fetched, and only once the in-flight queue is
        full; a FULL barrier (everything drained) runs only when host
        and device state must reconcile:

        * admission can make progress (a mid-prefill group, or a waiter
          with a free slot) — prefill bookkeeping and budget assembly
          need every in-flight token on the host;
        * a finish surfaced at a lazy drain — the freed slot/pages and
          the shrunken batch must be visible before the next dispatch;
        * page pressure (_ensure_or_preempt) — preemption must never
          reclaim pages a dispatched block still writes;
        * cancel() — same hazard, external trigger.

        Speculative mode (speculative_gamma > 0) runs the SAME pipeline
        with _spec_block in place of _decode_block: drafts come from
        the device-resident token history, acceptance is computed
        inside the scan, and the chained carry is (history, lengths,
        remaining budgets) instead of the final-token vector — no
        barrier per round (the pre-block-machinery implementation
        drained every round to draft on the host).

        Returns the number of tokens generated this round (throughput
        accounting for the serve loop)."""
        before = self._c_tokens.value
        rt = self.engine.runtime
        spec = self._spec_mode
        k = max(1, rt.decode_steps_per_tick)
        depth = max(1, rt.inflight_blocks)
        # tick-anatomy reset: zero the phase accumulator (sections add
        # their exclusive monotonic deltas below; drains self-accrue),
        # clear the barrier-cause list, zero the fetch wait
        t_tick0 = time.monotonic()
        tp = self._tick_phases
        for p in TICK_PHASES:
            tp[p] = 0.0
        self._tick_causes = []
        self._tick_fetch = 0.0
        # deadline scrub first: an expired request must not survive
        # into this tick's admission or decode dispatch (a drain it
        # forces accrues to drain_barrier, not to expire)
        d0 = self._drain_accrued()
        self._expire_due()
        self._phase_add("expire", max(0.0, time.monotonic() - t_tick0
                                      - (self._drain_accrued() - d0)))
        self._t_host0 = time.monotonic()
        self._had_inflight_at_host0 = bool(self._inflight)
        self._idle_at_host0 = self._had_inflight_at_host0 and \
            _device_ready(self._inflight[-1][1])
        # lazy drain: consume the oldest block once the queue is full
        # (depth=1 degenerates to the old drain-every-tick loop). A
        # finish surfacing there is a membership change -> full barrier.
        while len(self._inflight) >= depth:
            if self._drain_oldest():
                self._drain_inflight("finish")
        mixed = self._mixed_mode
        # seq-parallel long-prompt lane (ISSUE 20): at most one chunk
        # per tick — the lane's dispatch donates the pool binding, so
        # _sp_prefill_step drains in-flight blocks itself. The chunk's
        # per-device share counts against this tick's prefill budget
        # below (decode-ITL interference stays bounded by the declared
        # prefill_inline_budget just like ordinary chunked prefill).
        sp_used = 0
        if self._sp_enabled:
            t_sp = time.monotonic()
            self._sp_admit()
            sp_used = self._sp_prefill_step()
            self._phase_add("admit", time.monotonic() - t_sp)
        # admission barrier — retired as a class under mixed dispatch,
        # where admission is a host-side carry edit between dispatches
        # (_admit_inline) and the prompt rides the next fused block.
        # The alternating path still barriers whenever admission can
        # actually make progress, so a standing queue behind full
        # slots doesn't serialize the pipeline.
        if not mixed and (self._prefill_group
                          or (self.waiting
                              and self._free_slot() is not None)):
            self._drain_inflight("admission")
        t_admit = time.monotonic()
        if mixed:
            self._admit_inline()
        else:
            self._admit(sp_used // max(1, self.engine.sp_degree))
        self._phase_add("admit", time.monotonic() - t_admit)
        if self.running:
            self._h_batch.observe(len(self.running))
        # Preallocate pages for every step still in flight PLUS this
        # block up front: device lengths run ahead of the host mirror
        # by up to `step` tokens per undrained block (k samples for a
        # decode block, k rounds x (gamma+1) emissions for a spec
        # block), so the horizon is (inflight+1)*step + 1 (chain token
        # + the new samples) — and the block table dirties (syncs to
        # the device) at most once per TICK
        # (docs/decode_profile_r5.md capacity section). Any more would
        # add spurious page pressure in a tight pool; under pressure
        # _ensure_or_preempt falls back to a drain barrier before it
        # ever preempts. A spec verify's trailing writes past the
        # lifetime clamp land on the null page via the table default.
        step = k * self.engine.spec_emit_width if spec else k
        # tree mode (ISSUE 19): a round verifies N nodes but commits at
        # most D+1 = spec_emit_width tokens, and the accepted path is
        # COMPACTED from chunk positions as deep as base + N - 1 — the
        # accepted sources must sit on real pages (only the rejected
        # remainder may land on the null page), so both the horizon
        # and the lifetime clamp carry the N - (D+1) overhang
        tree_slack = 0
        if spec and self.engine.spec_tree_mode:
            tree_slack = (self.engine.spec_tree_geometry[1]
                          - self.engine.spec_emit_width)
        horizon = (len(self._inflight) + 1) * step + tree_slack + 1
        for req in list(self.running):
            if req in self.running:
                need = min(len(req.all_tokens) + horizon,
                           len(req.prompt) + req.max_new_tokens
                           + tree_slack)
                self._ensure_or_preempt(req, need)
        if mixed and self._prefill_group:
            # prefill lanes advance up to C tokens per scan step, so
            # their device write horizon is k*C per undrained block
            pf_h = (len(self._inflight) + 1) * k * self._mixed_chunk + 1
            for req in list(self._prefill_group):
                if req in self._prefill_group:
                    need = min(len(req.all_tokens) + pf_h,
                               len(req.prompt) + req.max_new_tokens)
                    self._ensure_or_preempt(req, need)
        t_disp = time.monotonic()
        a0 = tp["assemble"]
        if mixed:
            # the fused block covers both phases: its dispatch section
            # gets its own phase label so tick anatomy stays honest
            # about where admission+prefill time went
            dispatched = self._mixed_block(k)
            self._phase_add("mixed", max(0.0, time.monotonic() - t_disp
                                         - (tp["assemble"] - a0)))
        else:
            dispatched = self._spec_block(k) if spec \
                else self._decode_block(k)
            self._phase_add("dispatch",
                            max(0.0, time.monotonic() - t_disp
                                - (tp["assemble"] - a0)))
        if not dispatched and (self._inflight or self._pending_first):
            # nothing dispatchable (every budget is spent on device):
            # the remaining tokens exist only in flight — fetch them
            # now or the loop would spin forever. In spec mode this is
            # the budget-carry reconciliation (only the device knows
            # the remainders), hence the distinct cause label.
            self._drain_inflight("spec" if spec else "idle")
        self._g_inflight.set(len(self._inflight))
        made = int(self._c_tokens.value - before)
        if self.trace is not None:
            # one global event per tick: the decode batch this round —
            # slot composition plus what the stacked drain surfaced
            self.trace.event(None, "decode_tick",
                             batch=len(self.running),
                             waiting=len(self.waiting),
                             steps=k, block_steps=k, spec=spec,
                             inflight=len(self._inflight),
                             generated=made)
        self._record_tick(time.monotonic() - t_tick0, made, spec)
        return made

    def _drain_accrued(self) -> float:
        """Drain-owned phase time accrued so far this tick (plain dict
        reads): lets an enclosing section subtract the drains it
        triggered, keeping the phase sections non-overlapping."""
        tp = self._tick_phases
        return (tp["drain_barrier"] + tp["drain_oldest"]
                + tp["flush"] + tp["spec_emit"])

    def _record_tick(self, wall: float, made: int, spec: bool) -> None:
        """Close the tick's anatomy record: compute the residual
        ("other" = untimed host work — page prealloc, trace appends),
        feed the per-phase histograms, the host/device split, the
        timeline ring, and the flight-recorder trigger poll. Host
        arithmetic only — no device value is ever touched here."""
        tp = self._tick_phases
        known = sum(tp[p] for p in TICK_PHASES if p != "other")
        tp["other"] = max(0.0, wall - known)
        for name, h in self._h_phase.items():
            h.observe(tp[name])
        fetch = min(self._tick_fetch, wall)
        self._t_device_total += fetch
        self._t_host_total += max(0.0, wall - fetch)
        self.ticklog.record(wall, tp, fetch_s=fetch,
                            inflight=len(self._inflight),
                            barrier_causes=self._tick_causes,
                            batch=len(self.running),
                            waiting=len(self.waiting),
                            pages_free=self.alloc.free_pages,
                            generated=made, spec=spec)
        if self.flightrec is not None:
            self.flightrec.poll({
                "slo_burn_rate": self._g_slo_burn.value,
                "preemptions_total": self._c_preempt.value,
                "deadline_expired_total": sum(
                    c.value for c in self._c_deadline._children.values()),
                "queue_depth": float(len(self.waiting)),
                "kv_pages_free": float(self.alloc.free_pages)})
        ts = self.timeseries
        if ts is not None and ts.due():
            gauges, rates = self._timeseries_signals()
            ts.sample(gauges, rates=rates, t_wall=time.time())

    def _timeseries_signals(self):
        """The SignalRecorder's per-interval snapshot (gauges, rates):
        cheap host reads off the registry + tick anatomy. `rates` maps
        OUTPUT signal name -> CUMULATIVE counter value — the recorder
        turns them into per-second deltas (Counter.rate, clamped at 0
        across resets). Runs only when the recorder is due, never per
        tick."""
        snap = self.registry.snapshot()
        gauges = {
            "queue_depth": float(len(self.waiting)),
            "active_requests": float(len(self._all_live)),
            "inflight_depth": float(len(self._inflight)),
            "kv_pages_free": float(self.alloc.free_pages),
            "slo_burn_rate": self._g_slo_burn.value,
        }
        if self.host_tier is not None:
            gauges["kv_tier_hit_rate"] = self._tier_hit_rate()
        total = self._t_host_total + self._t_device_total
        if total > 0.0:
            gauges["tick_host_frac"] = self._t_host_total / total
        pp = self.ticklog.phase_percentiles()
        if pp:
            gauges["tick_phase_dominant_p95"] = max(
                v["p95"] for k, v in pp.items() if k != "other")
        rates = {
            "tokens_per_sec": snap.get("tokens_generated_total", 0.0),
            "preemptions_per_sec": snap.get("preemptions_total", 0.0),
            "shed_per_sec": snap.get("shed_total", 0.0),
            "deadline_expired_per_sec":
                snap.get("deadline_expired_total", 0.0),
        }
        for cause, v in self.barrier_causes().items():
            rates[f"barrier_{cause}_per_sec"] = v
        return gauges, rates

    def metrics(self) -> Dict[str, float]:
        """Legacy flat-dict view, assembled from the typed registry.

        NB: the raw-gap ITL percentiles carry PER-TICK-BURST semantics
        under pipelined dispatch — gaps are stamped at the stacked
        drain, so they bimodalize (p50 ~ 0, p95 ~ tick) — and are
        therefore exposed ONLY under itl_p50/p95/max_tick_burst
        (ISSUE 10 satellite: the degenerate bare itl_p50/itl_p95 keys
        are gone). The ITL metrics of record are itl_req_mean_* and
        the registry's real histograms (ttft_seconds,
        itl_req_mean_seconds); see obs/metrics.py HELP.
        """
        m: Dict[str, float] = {
            "requests_total": self._c_requests.value,
            "requests_finished": self._c_finished.value,
            "tokens_generated_total": self._c_tokens.value,
            "preemptions_total": self._c_preempt.value,
            "spec_forwards_total": self._c_spec_fwd.value,
            "spec_drafts_accepted_total": self._c_spec_acc.value,
            # compat: the unlabeled sum over the {cause} family — the
            # key every pre-ISSUE-15 consumer (spec bench, tests) reads
            "drain_barriers_total": sum(self.barrier_causes().values()),
        }
        if self._spec_mode:
            fwd = self._c_spec_fwd.value
            m["spec_block_tokens_total"] = self._c_spec_tok.value
            # the speculation headline: tokens each verify forward paid
            # for (1.0 = speculation is earning nothing over plain
            # decode; > 1 = drafts are landing)
            m["spec_tokens_per_forward"] = \
                self._c_spec_tok.value / fwd if fwd else 0.0
            h = self._h_accept
            m["spec_accept_rate"] = \
                h._sum / h._count if h._count else 0.0
        m["spec_mixed_fallback_total"] = self._c_spec_mixed_fb.value
        if self._mixed_fallback_reason is not None:
            # the one-line why (ISSUE 19 satellite): which engine gate
            # sent a requested mixed_dispatch back to the alternating
            # path — the only non-float value in this dict
            m["spec_mixed_fallback_reason"] = self._mixed_fallback_reason
        m["queue_depth"] = len(self.waiting)
        m["active_requests"] = len(self._all_live)
        m["kv_pages_free"] = self.alloc.free_pages
        m["kv_pages_total"] = self.alloc.num_pages
        if hasattr(self.alloc, "hit_tokens"):
            m["prefix_cache_hit_tokens"] = self.alloc.hit_tokens
            m["prefix_cache_lookup_tokens"] = self.alloc.lookup_tokens
        if self.host_tier is not None:
            st = self.host_tier.stats()
            m["kv_tier_pages"] = st["entries"] + st["spilled_entries"]
            m["kv_tier_bytes"] = st["bytes"]
            m["kv_tier_pages_saved_total"] = st["saves"]
            m["kv_tier_pages_restored_total"] = st["restores"]
            m["kv_tier_misses_total"] = st["misses"]
            m["kv_tier_spills_total"] = st["spills"]
            m["kv_tier_hit_rate"] = self._tier_hit_rate()
            if self._tier_restores:
                a = np.asarray(self._tier_restores)
                m["kv_tier_restore_seconds_p50"] = \
                    float(np.percentile(a, 50))
                m["kv_tier_restore_seconds_p95"] = \
                    float(np.percentile(a, 95))
        if self._ttfts:
            a = np.asarray(self._ttfts)
            m["ttft_p50"] = float(np.percentile(a, 50))
            m["ttft_p95"] = float(np.percentile(a, 95))
        if self._itls:
            # raw-gap percentiles carry per-tick-burst semantics under
            # pipelined dispatch (p50 is identically 0.0 between
            # burst-mates at decode_steps_per_tick > 1 — the r05
            # headline artifact), so they are ONLY exposed under the
            # explicit _tick_burst suffix; itl_req_mean_* is the ITL
            # metric of record
            a = np.asarray(self._itls)
            m["itl_p50_tick_burst"] = float(np.percentile(a, 50))
            m["itl_p95_tick_burst"] = float(np.percentile(a, 95))
            m["itl_max_tick_burst"] = float(a.max())
        if self._itl_means:
            a = np.asarray(self._itl_means)
            m["itl_req_mean_p50"] = float(np.percentile(a, 50))
            m["itl_req_mean_p95"] = float(np.percentile(a, 95))
        m["inflight_depth"] = float(self._g_inflight.value)
        m["deadline_expired_total"] = sum(
            c.value for c in self._c_deadline._children.values())
        m["shed_total"] = sum(
            c.value for c in self._c_shed._children.values())
        if self.slo_ttft_s is not None or self.slo_itl_s is not None:
            viol = sum(c.value for c in
                       self._c_slo_viol._children.values())
            ok = self._c_slo_ttft_ok.value + self._c_slo_itl_ok.value
            m["slo_ttft_ok_total"] = self._c_slo_ttft_ok.value
            m["slo_itl_ok_total"] = self._c_slo_itl_ok.value
            m["slo_violations_total"] = viol
            m["slo_burn_rate"] = self._g_slo_burn.value
            m["slo_attainment"] = ok / (ok + viol) if ok + viol else 1.0
        if self._bubbles:
            # device idle per dispatched block (0 = pipeline kept the
            # device busy through the tick's host section): the number
            # dispatch-ahead exists to drive to ~0
            a = np.asarray(self._bubbles)
            m["device_bubble_p50"] = float(np.percentile(a, 50))
            m["device_bubble_p95"] = float(np.percentile(a, 95))
        if self._kv_flushes:
            # write-combined KV window flush (kv_write_combine): host
            # wall per drain-time flush dispatch + tokens landed per
            # flush — the two numbers that say what one pool scatter
            # per drain costs and how much write combining it bought
            a = np.asarray(self._kv_flushes)
            m["kv_flush_p50"] = float(np.percentile(a, 50))
            m["kv_flush_p95"] = float(np.percentile(a, 95))
            m["kv_window_tokens_flushed_total"] = \
                self._c_kv_flushed.value
        # tick anatomy (ISSUE 15): per-phase p50/p95 over the timeline
        # ring window ("drain" = lazy + barrier drains combined — the
        # bench headline set), the host/device wall split, and the
        # dominant phase's p95 (the autoscale gauge: a host-bound
        # replica shows a fat admit/dispatch/drain phase, a
        # device-bound one a fat fetch share)
        pp = self.ticklog.phase_percentiles()
        for name in ("drain", "admit", "assemble", "dispatch",
                     "mixed", "expire", "spec_emit", "flush"):
            if name in pp:
                m[f"tick_phase_{name}_p50"] = pp[name]["p50"]
                m[f"tick_phase_{name}_p95"] = pp[name]["p95"]
        if pp:
            m["tick_phase_dominant_p95"] = max(
                v["p95"] for k, v in pp.items() if k != "other")
        total = self._t_host_total + self._t_device_total
        if total > 0:
            m["tick_host_frac"] = self._t_host_total / total
            m["tick_device_frac"] = self._t_device_total / total
        if self._mixed_mode:
            # prompt tokens that rode fused mixed blocks (ISSUE 18) —
            # under mixed dispatch ALL prefill work is inline, so this
            # pairs with drain_barriers admission == 0 as the evidence
            # that the admission barrier class is retired
            m["mixed_dispatch_prefill_tokens_inline"] = \
                float(self._inline_pf_tokens)
        if self._sp_enabled:
            m["seq_parallel_prefill_tokens_total"] = \
                self._c_sp_tokens.value
        return m

    def barrier_causes(self) -> Dict[str, float]:
        """Per-cause FULL-barrier counts: the drain_barriers_total
        {cause=} family as a plain dict (bench.py's breakdown key —
        which membership-change class is costing the pipeline)."""
        fam = self._c_barriers
        with fam._lock:
            items = list(fam._children.items())
        return {vals[0]: child.value for vals, child in items}

    # -- host KV tier hooks (cache/hosttier.py) ------------------------------

    def _tier_hit_rate(self) -> float:
        st = self.host_tier
        lookups = st.restores + st.misses
        return st.restores / lookups if lookups else 0.0

    def _tier_save(self, h: bytes, pid: int) -> None:
        """Allocator on_evict hook: demote the recycled page's bytes to
        the host tier. The page is registered (content-immutable) until
        this very moment, so the gather reads stable bytes; read_pages
        flushes the write-combined window itself if it is dirty. The
        allocator swallows exceptions — a failed demotion costs a
        future prefill, never correctness."""
        k, v, ks, vs = self.engine.read_pages([pid])
        self.host_tier.save(h, k[:, 0], v[:, 0],
                            None if ks is None else ks[:, 0],
                            None if vs is None else vs[:, 0])
        self._c_tier_saved.inc()

    def _tier_revive(self, h: bytes) -> Optional[int]:
        """Allocator reviver hook: on a registry miss during admission's
        prefix walk, pull the chain's next page back from the host tier
        into a freshly claimed page. Returns the page id (the walk
        continues as a normal prefix hit) or None on a tier miss /
        page exhaustion (the admission prefills the tail itself)."""
        t0 = time.monotonic()
        data = self.host_tier.load(h)
        if data is None:
            self._c_tier_miss.inc()
            self._g_tier_hit.set(self._tier_hit_rate())
            return None
        try:
            pid = self.alloc.import_page(h)
        except MemoryError:
            return None  # every page held by a live slot: no revive
        if pid is None:
            # digest already registered (idempotent re-import shape):
            # serve the walk from the live entry
            return self.alloc.lookup(h)
        k, v, ks, vs = data
        self.engine.write_pages(
            [pid], k[:, None], v[:, None],
            None if ks is None else ks[:, None],
            None if vs is None else vs[:, None])
        dt = time.monotonic() - t0
        self._h_tier_restore.observe(dt)
        self._tier_restores.append(dt)
        self._c_tier_restored.inc()
        self._g_tier_hit.set(self._tier_hit_rate())
        return pid

    # -- internals ----------------------------------------------------------

    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def _sp_qualifies(self, req: Request) -> bool:
        """Does this prompt belong to the seq-parallel long-prompt
        lane? (The normal admission loops break on a qualifying head
        so the lane keeps FCFS order — a long prompt waits for the
        lane, it never falls back to a single-device prefill.)"""
        return (self._sp_enabled and len(req.all_tokens)
                > self.engine.runtime.seq_parallel_threshold)

    def _sp_admit(self) -> None:
        """Admit the head-of-queue request into the seq-parallel lane
        when it qualifies and the lane is empty: pages for the WHOLE
        prompt (+1 for the first decode token) are allocated up front —
        every chunk scatters straight into the pool, so there is no
        later growth point mid-prefill."""
        if not self._sp_enabled or self._sp_group or not self.waiting:
            return
        req = self.waiting[0]
        if not self._sp_qualifies(req):
            return
        slot = self._free_slot()
        if slot is None:
            return
        if self._shares_inflight_prefix(req):
            return  # defer: a gang member is writing req's prefix
        cached = self.alloc.admit(slot, req.all_tokens,
                                  len(req.all_tokens) + 1)
        if cached is None:
            return  # pool exhausted; decode will free/preempt
        self.waiting.popleft()
        req.slot, req.state = slot, "prefilling"
        req.prefilled = req.cached_at_admit = cached
        self.slots[slot] = req
        self._sp_group.append(req)
        self.engine.set_table_row(slot, self.alloc.pages_of(slot))
        self._epoch += 1  # membership changed: operands rebuild
        wait = time.monotonic() - req.t_enqueued
        self._h_queue_wait.observe(wait)
        if self.flightrec is not None:
            self.flightrec.note("admit", id=req.id, slot=slot,
                                queue_wait_s=wait, cached=cached,
                                seq_parallel=True)
        if self.trace is not None:
            self.trace.event(req.id, "admit", slot=slot,
                             queue_wait_s=wait,
                             prefix_cache_hit_tokens=cached,
                             resumed=req.preemptions > 0,
                             seq_parallel=True)

    def _sp_prefill_step(self) -> int:
        """Dispatch ONE seq-parallel prefill chunk for the lane's
        request (engine.sp_prefill_chunk). Returns the prompt tokens
        dispatched (0 = lane empty or blocked).

        The chunk program donates the newest pool binding, so any
        in-flight decode blocks drain first — the established donation
        barrier (same hazard as admission prefills on the alternating
        path). On completion the request leaves through
        _finish_prefill like any gang member: pages publish to the
        prefix registry and the first token samples from the chunk's
        last-position logits."""
        if not self._sp_group:
            return 0
        req = self._sp_group[0]
        if self._inflight or self._pending_first:
            self._drain_inflight("sp_prefill")
            if req.done or req.slot is None:
                return 0  # the drain finished or preempted it
        toks = req.all_tokens
        chunk = toks[req.prefilled:req.prefilled + self._sp_chunk]
        if not chunk:
            return 0
        if self.trace is not None:
            self.trace.event(req.id, "sp_prefill_chunk",
                             start=req.prefilled, tokens=len(chunk),
                             degree=self.engine.sp_degree)
        logits = self.engine.sp_prefill_chunk(req.slot, chunk,
                                              req.prefilled)
        req.prefilled += len(chunk)
        self._c_sp_tokens.inc(len(chunk))
        if req.prefilled >= len(toks):
            # logits is [V] — _finish_prefill samples from [M, V] rows
            self._finish_prefill([req], logits[None, :])
            # mixed carries: the slot enters decode phase (plen 0); its
            # pool length was set by the chunk dispatches themselves
            self._plen_host[req.slot] = 0
        return len(chunk)

    def _admit(self, sp_spent: int = 0) -> None:
        """Group admission: gang-admit waiting requests and run the
        prefill group's next chunks as batched dispatches, repeating
        while budget remains and progress is possible (a round whose
        members all complete cheaply leaves budget for another gang).

        `sp_spent`: per-shard prompt tokens the seq-parallel lane
        already dispatched this tick — it counts against the tick's
        prefill budget so a tick never chews more than ~prefill_chunk
        tokens per device."""
        rt = self.engine.runtime
        if rt.scheduler == "static":
            # Static batching: no interleave — admit (and fully prefill)
            # whole batches only once the previous batch has drained;
            # budget None = whole prompts at once.
            if self.running or self._prefill_group:
                return
            budget = None
        else:
            budget = max(1, rt.prefill_chunk) - sp_spent
            if budget <= 0:
                return
        while True:
            used = self._admit_round(budget)
            if used is None:
                return
            if budget is not None:
                budget -= used
                if budget <= 0:
                    return

    def _admit_inline(self) -> None:
        """Mixed-dispatch admission (ISSUE 18): pull waiting requests
        into free slots WITHOUT a drain barrier or a separate prefill
        dispatch — the prompt rides the next fused block's prefill
        lanes. Admission here is pure host bookkeeping plus per-slot
        device carry edits between dispatches (_seed_mixed_slot, the
        established reset_slot pattern: ``.at[slot].set`` on arrays
        in-flight blocks never touch for a free slot).

        The concurrent-prefill cap (_mixed_max_pf, derived from
        RuntimeConfig.prefill_inline_budget) bounds how many slots may
        be in prefill phase at once — with chunk width C per slot per
        scan step, at most ~prefill_inline_budget prompt tokens are
        chewed per step while decode slots wait on that step's
        forward. That bound IS the ITL-tail knob."""
        admitted = False
        while (self.waiting
               and len(self._prefill_group) < self._mixed_max_pf):
            slot = self._free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            if self._sp_qualifies(req):
                break  # long prompt: waits for the seq-parallel lane
            if self._shares_inflight_prefix(req):
                break  # defer: a gang member is writing req's prefix
            cached = self.alloc.admit(slot, req.all_tokens,
                                      len(req.all_tokens) + 1)
            if cached is None:
                break  # pool exhausted; decode will free/preempt
            self.waiting.popleft()
            req.slot, req.state = slot, "prefilling"
            req.prefilled = req.cached_at_admit = cached
            self.slots[slot] = req
            self._prefill_group.append(req)
            self.engine.set_table_row(slot, self.alloc.pages_of(slot))
            self._seed_mixed_slot(req)
            admitted = True
            wait = time.monotonic() - req.t_enqueued
            self._h_queue_wait.observe(wait)
            if self.flightrec is not None:
                self.flightrec.note("admit", id=req.id, slot=slot,
                                    queue_wait_s=wait, cached=cached)
            if self.trace is not None:
                self.trace.event(req.id, "admit", slot=slot,
                                 queue_wait_s=wait,
                                 prefix_cache_hit_tokens=cached,
                                 resumed=req.preemptions > 0)
        if admitted:
            self._epoch += 1  # membership changed: operands rebuild

    def _seed_mixed_slot(self, req: Request) -> None:
        """Device-carry seeding for one mixed-dispatch admission. Every
        write is an ``.at[slot].set`` on the CURRENT carry binding —
        i.e. on the result of the newest in-flight block — so it lands
        after that block in device program order. The slot is free in
        every in-flight block's snapshot (inactive lanes advance
        nothing and their writes land on the null page), so nothing
        here races a dispatched program.

        Seeds: pool lengths at the cached prefix (the warm-prefix
        contract), window count at zero, the chunk cursor at the
        cached prefix, and the prompt tokens — into the prompt-buffer
        row (plain mixed) or the token-history row (spec mixed, where
        history doubles as the prompt buffer and the budget injects
        into the device remainder carry when one is live)."""
        eng = self.engine
        slot, toks = req.slot, req.all_tokens
        cached = req.cached_at_admit
        with eng._mesh_ctx():
            eng.cache = eng.cache._replace(
                lengths=eng.cache.lengths.at[slot].set(cached))
            if eng._win_len is not None:
                eng._win_len = eng._win_len.at[slot].set(0)
            cur = self._cursor_dev if self._cursor_dev is not None \
                else jnp.zeros((eng.num_slots,), jnp.int32)
            self._cursor_dev = cur.at[slot].set(cached)
            self._plen_host[slot] = len(toks)
            if self._spec_mode:
                row = np.zeros((self._hist_dev.shape[1],), np.int32)
                row[:len(toks)] = toks
                self._hist_dev = self._hist_dev.at[slot].set(
                    jnp.asarray(row))
                self._hist_len_dev = self._hist_len_dev.at[slot].set(
                    len(toks))
                if self._spec_rem is not None:
                    self._spec_rem = self._spec_rem.at[slot].set(
                        req.max_new_tokens - len(req.output))
            else:
                if self._pbuf_dev is None:
                    self._pbuf_dev = jnp.zeros(
                        (eng.num_slots, eng.cache.max_seq), jnp.int32)
                row = np.zeros((self._pbuf_dev.shape[1],), np.int32)
                row[:len(toks)] = toks
                self._pbuf_dev = self._pbuf_dev.at[slot].set(
                    jnp.asarray(row))

    def _admit_round(self, budget: Optional[int]) -> Optional[int]:
        """One gang-admission round: pull waiting requests into the
        prefill group (bounded by free slots, pages, prefill_max_batch,
        and the remaining token budget), pack every member's next chunk
        under the budget FCFS, and dispatch the chunks as batched
        [B, Tbucket] prefills bucketed by chunk length (plus freshness
        only when engine.prefill_gang_split_fresh — the seed rule,
        kept for prefill_flash_warm=False).

        Returns the number of prompt tokens dispatched, or None if no
        progress was possible (nothing admissible and nothing to
        prefill)."""
        rt = self.engine.runtime
        cap = max(1, min(rt.prefill_max_batch, self.engine.num_slots))
        demand = sum(len(r.all_tokens) - r.prefilled
                     for r in self._prefill_group)
        while (self.waiting and len(self._prefill_group) < cap
               and (budget is None or demand < budget)):
            slot = self._free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            if self._sp_qualifies(req):
                break  # long prompt: waits for the seq-parallel lane
            if self._shares_inflight_prefix(req):
                break  # defer: a gang member is writing req's prefix
            # all_tokens includes output if preempted earlier; admit
            # may attach already-cached prefix pages (prefix caching),
            # whose tokens skip prefill entirely via the warm path.
            cached = self.alloc.admit(slot, req.all_tokens,
                                      len(req.all_tokens) + 1)
            if cached is None:
                break  # pool exhausted; decode will free/preempt
            self.waiting.popleft()
            req.slot, req.state = slot, "prefilling"
            req.prefilled = req.cached_at_admit = cached
            self.slots[slot] = req
            self._prefill_group.append(req)
            self.engine.set_table_row(slot, self.alloc.pages_of(slot))
            demand += len(req.all_tokens) - cached
            wait = time.monotonic() - req.t_enqueued
            self._h_queue_wait.observe(wait)
            if self.flightrec is not None:
                self.flightrec.note("admit", id=req.id, slot=slot,
                                    queue_wait_s=wait, cached=cached)
            if self.trace is not None:
                self.trace.event(req.id, "admit", slot=slot,
                                 queue_wait_s=wait,
                                 prefix_cache_hit_tokens=cached,
                                 resumed=req.preemptions > 0)
            # (no length bookkeeping for `cached` needed: the member's
            # first warm chunk sets lengths[slot] = cached + len(chunk))
        if not self._prefill_group:
            return None

        # pack each member's next chunk under the budget, FCFS — members
        # admitted earlier win budget, exactly like the old serialized
        # admission, so carried members can't starve behind new arrivals
        plan: List[tuple] = []  # (req, chunk, start)
        used = 0
        for req in self._prefill_group:
            room = None if budget is None else budget - used
            if room is not None and room <= 0:
                break
            prefix = req.all_tokens
            end = len(prefix) if room is None \
                else min(len(prefix), req.prefilled + room)
            chunk = prefix[req.prefilled:end]
            if not chunk:
                continue
            plan.append((req, chunk, req.prefilled))
            used += len(chunk)
        if not plan:
            return None

        # bucket by (freshness, padded chunk length): members sharing a
        # bucket ride ONE [B, Tbucket] dispatch. Freshness splits the
        # gang ONLY when the engine's fresh program is kernelized but
        # its warm one is dense (prefill_gang_split_fresh) — there a
        # warm prefix-cache or carried member would drag cold members
        # off the flash path. With warm-prefix flash (ISSUE 13, the
        # default where kernels run) the warm program takes the kernel
        # too, so mixed gangs ride one dispatch and the all-or-nothing
        # freshness downgrade is gone.
        split_fresh = self.engine.prefill_gang_split_fresh
        hi = self.engine.cache.max_seq
        dispatches: Dict[tuple, List[tuple]] = {}
        for req, chunk, start in plan:
            key = (start == 0 if split_fresh else True,
                   bucket_len(len(chunk), hi=hi))
            dispatches.setdefault(key, []).append((req, chunk, start))
        for (_, bucket), members in dispatches.items():
            self._h_prefill_batch.observe(len(members))
            if self.trace is not None:
                self.trace.event(None, "prefill_batch",
                                 members=len(members),
                                 slots=[m[0].slot for m in members],
                                 bucket=bucket,
                                 tokens=sum(len(m[1]) for m in members),
                                 fresh=all(m[2] == 0 for m in members))
                for req, chunk, start in members:
                    self.trace.event(req.id, "prefill_chunk",
                                     start=start, tokens=len(chunk))
            logits = self.engine.prefill_batch(
                [m[0].slot for m in members], [m[1] for m in members],
                [m[2] for m in members])
            done_rows, done_reqs = [], []
            for i, (req, chunk, start) in enumerate(members):
                req.prefilled = start + len(chunk)
                if req.prefilled >= len(req.all_tokens):
                    done_rows.append(i)
                    done_reqs.append(req)
            if done_reqs:
                # device-side row gather: completing members' first
                # tokens sample from THIS dispatch, no host sync
                self._finish_prefill(done_reqs,
                                     logits[jnp.asarray(done_rows)])
        return used

    def _shares_inflight_prefix(self, req: Request) -> bool:
        """Prefix caching only: would `req` hit KV pages a current gang
        member is still writing? Serialized admission accidentally
        guaranteed that a request arriving behind a same-prefix request
        admitted AFTER the first registered its pages — and so shared
        them. Gang admission would put both in one group and pay the
        shared prefix's prefill twice. Keep the guarantee deliberately:
        if req's leading full block chain-matches an in-flight member's,
        defer its admission one round — the member registers at
        prefill_done and req then admits with a cache hit. FIFO order is
        preserved (admission simply stops for the round), matching the
        old behavior where such a request blocked behind the serialized
        prefill anyway."""
        if not self.engine.runtime.prefix_caching or not self._prefill_group:
            return False
        from butterfly_tpu.cache.prefix import chain_block_hashes
        ps = self.alloc.page_size
        head = chain_block_hashes(req.all_tokens, ps, 1)
        if not head:  # shorter than one block: nothing cacheable
            return False
        return any(chain_block_hashes(m.all_tokens, ps, 1) == head
                   for m in self._prefill_group)

    def _finish_prefill(self, reqs: List[Request], logits) -> None:
        """Members whose prompt is now fully in cache: publish pages for
        prefix reuse (no-op without prefix caching), sample every
        member's first token ON DEVICE from the shared dispatch's logits
        [M, V] in one vectorized draw, start decoding. Tokens are
        fetched at the next stacked drain; even a max_new==1 request
        keeps its slot until then (its extra decode steps are discarded
        like any post-finish in-flight work)."""
        for req in reqs:
            self.alloc.register(req.slot, req.all_tokens)
            if req in self._prefill_group:
                self._prefill_group.remove(req)
            else:  # the seq-parallel lane finishes through here too
                self._sp_group.remove(req)
            req.state = "running"
            self.running.append(req)
            ran = len(req.all_tokens) - req.cached_at_admit
            self._h_prefill_tokens.observe(ran)
            if self.trace is not None:
                self.trace.event(req.id, "prefill_done", tokens=ran,
                                 total=len(req.all_tokens))
        self._key, sub = jax.random.split(self._key)
        firsts = sample_batched(
            logits, sub,
            np.asarray([r.temperature for r in reqs], np.float32),
            self.engine.runtime_top_k, self.engine.runtime_top_p)
        base = self._next_dev if self._next_dev is not None \
            else jnp.asarray(self._next_tokens)
        slots_arr = np.asarray([r.slot for r in reqs], np.int32)
        self._next_dev = base.at[slots_arr].set(firsts)
        if self._spec_mode:
            # seed the device-side token history the on-device drafter
            # reads: the full prompt (+ prior output on readmission)
            # from the host, plus the device-resident first token —
            # no host sync, the spec block chains on this carry
            H = self._hist_dev.shape[1]
            rows = np.zeros((len(reqs), H), np.int32)
            lens = np.zeros((len(reqs),), np.int32)
            for i, req in enumerate(reqs):
                toks = req.all_tokens
                rows[i, :len(toks)] = toks
                lens[i] = len(toks)
            # a model draft source reseeds the members' draft KV from
            # the SAME rows (first token excluded — the draft_len ==
            # hist_len - 1 invariant); no-op for stateless sources.
            # Admission runs behind a full drain barrier, so no spec
            # block is in flight against the donated draft state.
            self.engine.draft_prefill(slots_arr, rows, lens)
            self._hist_dev = self._hist_dev.at[slots_arr].set(
                jnp.asarray(rows)).at[slots_arr, lens].set(firsts)
            self._hist_len_dev = self._hist_len_dev.at[slots_arr].set(
                jnp.asarray(lens + 1))
        for i, req in enumerate(reqs):
            self._pending_first.append(
                (req, req.preemptions, req.slot, firsts[i]))
            self._pending_first_keys.add((req.id, req.preemptions))
        self._epoch += 1  # running set + pending-first set changed

    def _decode_block(self, k: int) -> bool:
        """Dispatch ONE fused k-step decode block for the running set
        (engine.decode_block_async), chained on the previous block's
        device-resident carry — the previous block need NOT be drained
        first (dispatch-ahead). Host work — operand assembly, the
        jnp.asarray conversions, the RNG split, the dispatch itself —
        is paid once per BLOCK instead of once per token, and the
        operand assembly itself is cached on the batch-membership
        epoch: back-to-back blocks over an unchanged batch reuse the
        active/temps/stops arrays and the slot snapshot, refreshing
        only the budget vector (base minus the steps already in
        flight — the device decrements its own copy inside each scan,
        so the host estimate must run ahead the same way). Page growth
        happened at tick start (the len + (inflight+1)*k + 1
        preallocation covers every step of every undrained scan).

        Per-slot stop ids and remaining-token budgets ride into the
        scan so a slot that finishes mid-block is masked ON DEVICE
        (lengths freeze, writes land on the null page) rather than
        generating garbage the drain discards; a finished slot's chain
        token stays frozen at its stop id, so every later in-flight
        block starts it dead too.

        Returns True iff a block was dispatched.
        """
        if not self.running:
            return False
        active, temps, stops, base, specm, snapshot = self._assemble()
        # steps dispatched but undrained: the device consumed (at most)
        # this much of each live slot's budget already. A slot that
        # went dead early consumed less, but its chain token is frozen
        # at its stop id (or its budget is genuinely spent), so
        # under-budgeting it cannot drop real tokens.
        ahead = sum(e[3] for e in self._inflight)
        budgets = np.maximum(base - ahead, 0) if ahead else base
        if not (active & (budgets > 0)).any():
            return False  # every runner is out of budget on device
        self._key, sub = jax.random.split(self._key)
        # chain on the device token vector admissions write into (which
        # the previous block's final vector seeded); the host vector
        # only on the cold first dispatch
        cur = self._next_dev if self._next_dev is not None \
            else self._next_tokens
        block, final = self.engine.decode_block_async(
            cur, active, temps, stops, budgets, sub, k)
        self._next_dev = final
        self._inflight.append(("decode", final, block, k, snapshot,
                               time.monotonic()))
        self._note_bubble()
        return True

    def _assemble(self) -> tuple:
        """Per-block host operands — the active/temps/stops/base-budget
        /spec-mask arrays and the slot snapshot — cached on the batch-
        membership epoch: back-to-back blocks over an unchanged batch
        skip the per-slot Python rebuild and the np.asarray churn.

        Mixed dispatch extends the batch to prefill-group members too:
        their lanes ride the same block (phase decided on device by
        cursor < plen), and their budget is the full remaining
        emission allowance (output is empty unless resumed from a
        preemption)."""
        if self._operands_epoch != self._epoch:
            t0 = time.monotonic()
            S = self.engine.num_slots
            active = np.zeros((S,), bool)
            temps = np.zeros((S,), np.float32)
            stops = np.full((S,), -1, np.int32)
            base = np.zeros((S,), np.int32)
            specm = np.zeros((S,), bool)
            # seq-parallel-lane members never ride a block: their
            # prefill happens in dedicated sp_prefill_chunk dispatches
            # and they enter `running` only via _finish_prefill.
            batch = (list(self.running) + list(self._prefill_group)
                     if self._mixed_mode else self.running)
            for req in batch:
                active[req.slot] = True
                temps[req.slot] = req.temperature
                stops[req.slot] = req.stop_token
                specm[req.slot] = req.speculative
                # tokens the request may still emit: max_new minus what
                # the host has drained, minus an undrained
                # admission-time first token (queued in _pending_first;
                # set lookup — the old per-runner linear scan over the
                # pending list was O(running x pending) every block)
                pending = (req.id,
                           req.preemptions) in self._pending_first_keys
                base[req.slot] = (req.max_new_tokens - len(req.output)
                                  - int(pending))
            self._operands = (active, temps, stops, base, specm,
                              {req.slot: (req, req.preemptions)
                               for req in batch})
            self._operands_epoch = self._epoch
            self._phase_add("assemble", time.monotonic() - t0)
        return self._operands

    def _note_bubble(self) -> None:
        if self._idle_at_host0:
            # the newest in-flight carry was already materialized when
            # this tick's host section began: the device sat idle
            # through all of it — the bubble dispatch-ahead closes
            bubble = time.monotonic() - self._t_host0
            self._h_bubble.observe(bubble)
            self._bubbles.append(bubble)
        elif self._had_inflight_at_host0:
            self._h_bubble.observe(0.0)
            self._bubbles.append(0.0)
        self._idle_at_host0 = self._had_inflight_at_host0 = False

    def _spec_block(self, rounds: int) -> bool:
        """Dispatch ONE fused speculative block (engine.spec_block_async)
        — `rounds` chained draft → batched-multi-slot-verify →
        on-device-accept rounds — chained on the device-resident
        history/budget carry exactly like _decode_block chains on the
        final-token vector, so `inflight_blocks >= 2` pipelines spec
        rounds with host scheduling (no full drain barrier per round:
        the old host accept loop drained EVERY round).

        Budgets: the first dispatch after a full barrier seeds the
        device budget vector from exact host state (base, minus
        nothing — the barrier drained every in-flight token); chained
        dispatches thread the previous block's device-resident
        remainder through, because a spec block's consumption is
        variable (1..gamma+1 tokens per live slot per round) and only
        the device knows it before the drain. Membership changes force
        a barrier anyway, so the carry is always exact.

        Returns True iff a block was dispatched."""
        if not self.running:
            return False
        active, temps, stops, base, specm, snapshot = self._assemble()
        if self._spec_rem is None:
            if not (active & (base > 0)).any():
                return False  # everything already emitted (undrained)
            budgets = base
        else:
            # device carry: exact remainder after every in-flight
            # round. The host cannot cheaply inspect it; dispatching a
            # potentially-empty block is safe — each tick still drains
            # the oldest block, so finishes keep surfacing and the
            # barrier-on-finish resets the carry to host truth.
            budgets = self._spec_rem
        self._key, sub = jax.random.split(self._key)
        toks, valid, hist, hlen, rem = self.engine.spec_block_async(
            self._hist_dev, self._hist_len_dev, active, temps, stops,
            budgets, specm, sub, rounds)
        self._hist_dev, self._hist_len_dev, self._spec_rem = hist, hlen, rem
        self._inflight.append(("spec", hlen, (toks, valid), rounds,
                               snapshot, time.monotonic()))
        self._note_bubble()
        return True

    def _mixed_block(self, k: int) -> bool:
        """Dispatch ONE fused MIXED block (ISSUE 18): decode (or spec)
        lanes and prefill lanes ride the same k-step jitted program
        (engine.mixed_block_async / mixed_spec_block_async), chained
        on the device carries exactly like _decode_block/_spec_block —
        one dispatch per tick covering both phases.

        The host runs a cheap lockstep simulation of each prefill
        lane's cursor: chunk progress is deterministic while a lane is
        live (a prefilling lane cannot die mid-prompt — its first
        possible emission is the completion-sampled first token), so
        ``req.prefilled`` advances to the block's post-state at
        DISPATCH time and the completion set rides the in-flight entry
        for drain-time state transitions (_mixed_transitions). For
        plain mixed the same simulation also yields per-slot emission
        counts, the budget look-ahead chained dispatches subtract
        (stop-deaths make it an over-estimate, which is safe for the
        same frozen-chain-token reason as _decode_block). Spec mixed
        instead threads the device-resident remainder carry through,
        exactly like _spec_block.

        Returns True iff a block was dispatched."""
        if not (self.running or self._prefill_group):
            return False
        active, temps, stops, base, specm, snapshot = self._assemble()
        S = self.engine.num_slots
        self._key, sub = jax.random.split(self._key)
        plen = self._plen_host
        cursor = self._cursor_dev if self._cursor_dev is not None \
            else jnp.zeros((S,), jnp.int32)
        if self._spec_mode:
            C = self._mixed_chunk  # gamma + 1: the verify shape
            if self._spec_rem is None:
                if not (active & (base > 0)).any():
                    return False  # everything already emitted (undrained)
                budgets = base
            else:
                budgets = self._spec_rem
            # deterministic cursor advance: C prompt tokens per round
            # while mid-prefill (emissions can't kill the lane first)
            pf_done = []
            for req in list(self._prefill_group):
                p = int(plen[req.slot])
                if req.prefilled < p:
                    adv = min(p, req.prefilled + k * C)
                    self._inline_pf_tokens += adv - req.prefilled
                    req.prefilled = adv
                if req.prefilled >= p:
                    pf_done.append(req.slot)
            toks, valid, hist, hlen, rem, cursor = \
                self.engine.mixed_spec_block_async(
                    self._hist_dev, self._hist_len_dev, cursor, plen,
                    active, temps, stops, budgets, specm, sub, k)
            self._hist_dev, self._hist_len_dev = hist, hlen
            self._spec_rem, self._cursor_dev = rem, cursor
            self._inflight.append(("mixed_spec", hlen, (toks, valid), k,
                                   snapshot, time.monotonic(), pf_done,
                                   None))
            self._note_bubble()
            return True
        # plain mixed: chunk width C only while a prompt is actually in
        # flight — with no prefill lane the program collapses to C=1,
        # the exact _decode_scan shape (and its RNG stream)
        C = self._mixed_chunk if self._prefill_group else 1
        ahead = np.zeros((S,), np.int64)
        for ent in self._inflight:
            ahead = ahead + ent[7]  # per-slot emission estimates
        budgets = np.maximum(base - ahead, 0).astype(np.int32)
        if not (active & (budgets > 0)).any():
            return False  # every lane is out of budget on device
        # lockstep host sim per lane: cursor end-state, emission count,
        # completion membership. Mirrors the device scan exactly up to
        # stop-deaths, which only shrink emissions after the fact.
        emit_vec = np.zeros((S,), np.int32)
        pf_done = []
        for slot, (req, _gen) in snapshot.items():
            b = int(budgets[slot])
            if not active[slot] or b <= 0:
                continue
            c, p, e = req.prefilled, int(plen[slot]), 0
            for _ in range(k):
                if c < p:
                    c = min(p, c + C)
                    if c < p:
                        continue
                e += 1  # completion first token, or a decode step
                if e >= b:
                    break
            if c != req.prefilled:
                self._inline_pf_tokens += c - req.prefilled
                req.prefilled = c
            emit_vec[slot] = e
            if req.state == "prefilling" and c >= p:
                pf_done.append(slot)
        cur = self._next_dev if self._next_dev is not None \
            else self._next_tokens
        if self._pbuf_dev is None:
            self._pbuf_dev = jnp.zeros((S, self.engine.cache.max_seq),
                                       jnp.int32)
        block, valid, final, cursor = self.engine.mixed_block_async(
            cur, cursor, self._pbuf_dev, plen, active, temps, stops,
            budgets, sub, k, C)
        self._next_dev, self._cursor_dev = final, cursor
        self._inflight.append(("mixed", final, (block, valid), k,
                               snapshot, time.monotonic(), pf_done,
                               emit_vec))
        self._note_bubble()
        return True

    def _drain_inflight(self, cause: str = "finish") -> bool:
        """FULL drain barrier: fetch every pending first token and
        in-flight block in ONE stacked device read. Returns True if any
        request finished. In spec mode the device budget carry resets
        to None — the host again knows every emitted token, so the
        next dispatch reseeds it from exact host state.

        `cause` labels the barrier in drain_barriers_total{cause=}
        (the membership-change class that forced it: admission, finish,
        page_pressure, cancel, spec, idle, expired, flush) and rides
        the tick's timeline record + the flight-recorder ring."""
        t0 = time.monotonic()
        if self._inflight or self._pending_first:
            self._c_barriers.labels(cause).inc()
            self._tick_causes.append(cause)
            if self.flightrec is not None:
                self.flightrec.note("barrier", cause=cause,
                                    inflight=len(self._inflight))
        blocks, self._inflight = self._inflight, []
        self._spec_rem = None
        tp = self._tick_phases
        sub0 = tp["flush"] + tp["spec_emit"]
        out = self._drain_blocks(blocks)
        self._phase_add("drain_barrier",
                        max(0.0, time.monotonic() - t0
                            - (tp["flush"] + tp["spec_emit"] - sub0)))
        return out

    def _drain_oldest(self) -> bool:
        """Lazy-drain step: fetch the pending firsts and ONLY the
        oldest in-flight block, leaving newer blocks running on the
        device (the dispatch-ahead overlap — the device computes block
        t+1 while the host emits block t). Returns True if any request
        finished (the caller escalates that to a full barrier)."""
        t0 = time.monotonic()
        tp = self._tick_phases
        sub0 = tp["flush"] + tp["spec_emit"]
        out = self._drain_blocks([self._inflight.pop(0)]
                                 if self._inflight else [])
        self._phase_add("drain_oldest",
                        max(0.0, time.monotonic() - t0
                            - (tp["flush"] + tp["spec_emit"] - sub0)))
        return out

    def _drain_blocks(self, blocks: List[tuple]) -> bool:
        """Fetch + emit the given decode blocks (ONE stacked device
        fetch) and do their host bookkeeping in chronological order.
        Pending first tokens always ride along: they are queued at an
        admission barrier, when nothing is in flight, so they predate
        every dispatched block; each block's [k, S] rows are then
        emitted in step order per live slot, truncated per request at
        its stop token / max_new by _emit.

        Requests that finished, were cancelled, or were preempted
        between dispatch and drain have their tokens discarded — the
        generation check catches even a preemption readmitted into the
        SAME slot. Slots that went dead mid-block carry frozen repeats
        of their last token, which the done-break below skips (the
        device stopped their writes and length growth inside the scan).
        """
        # Flush the write-combined KV window FIRST (kv_write_combine):
        # the flush dispatch lands after every staged block in device
        # order, so by the time an emission below finishes a request —
        # registering its pages for prefix reuse and releasing them for
        # reclaim — every staged K/V byte is in the pool. No-op (None)
        # when nothing is staged; the flushed-token count is a device
        # scalar that rides this drain's one stacked fetch.
        t_flush = time.monotonic()
        flushed = self.engine.flush_kv_window()
        if flushed is not None:
            dt = time.monotonic() - t_flush
            self._h_kv_flush.observe(dt)
            self._kv_flushes.append(dt)
            self._phase_add("flush", dt)
            if self.flightrec is not None:
                self.flightrec.note("flush", dispatch_s=dt)
        firsts, self._pending_first = self._pending_first, []
        self._pending_first_keys.clear()  # refreshed: all entries drain
        if not blocks and not firsts:
            if flushed is not None:
                self._c_kv_flushed.inc(int(flushed))
            return False
        finished_before = self._c_finished.value
        C = self.engine.spec_emit_width
        parts = [f[3].reshape(1) for f in firsts]
        for ent in blocks:
            if ent[0] == "decode":
                parts.append(ent[2].reshape(-1))
            else:  # spec/mixed: stacked emissions + validity mask ride
                # the same single fetch (bool widened to the int dtype)
                toks3, valid3 = ent[2]
                parts.append(toks3.reshape(-1))
                parts.append(valid3.astype(jnp.int32).reshape(-1))
        if flushed is not None:
            parts.append(flushed.reshape(1))  # trailing; offsets unaffected
        # the ONE stacked device fetch: the only tick section that
        # blocks on the device — timed for the tick_host_frac /
        # tick_device_frac split (everything else in a tick is host).
        # device_get issues every part's host copy async before the
        # first blocking read, then the concat is pure host numpy — a
        # device-side jnp.concatenate over parts with mixed shardings
        # miscompiles under an active mesh on jax 0.4.x (a 3-part
        # concat comes back with every element summed over the seq
        # shards, i.e. multiplied by the seq degree).
        t_fetch = time.monotonic()
        vals = np.concatenate(jax.device_get(parts)) if len(parts) > 1 \
            else np.asarray(parts[0])
        self._tick_fetch += time.monotonic() - t_fetch
        if flushed is not None:
            self._c_kv_flushed.inc(int(vals[-1]))
        now = time.monotonic()
        nf = len(firsts)
        S = self.engine.num_slots
        for (req, gen, slot, _), tok in zip(firsts, vals[:nf]):
            # stale if the request was cancelled or preempted (a
            # readmission queues a fresh entry with a new generation)
            if req.done or req.slot != slot or req.preemptions != gen:
                continue
            self._next_tokens[slot] = int(tok)
            self._emit(req, int(tok))
        off = nf
        for ent in blocks:
            kind, _, _, k, snapshot, t_dispatch = ent[:6]
            self._h_decode_block.observe(now - t_dispatch)
            if kind in ("mixed", "mixed_spec"):
                # prefill lanes that completed inside this block leave
                # the prefill group BEFORE their first token (riding
                # the block's emission arrays) is emitted below
                self._mixed_transitions(ent[6], snapshot)
            if kind in ("spec", "mixed_spec"):
                toks3 = vals[off:off + k * S * C].reshape(k, S, C)
                off += k * S * C
                valid3 = vals[off:off + k * S * C].reshape(k, S, C) != 0
                off += k * S * C
                t_se = time.monotonic()
                self._emit_spec(toks3, valid3, snapshot)
                self._phase_add("spec_emit", time.monotonic() - t_se)
                continue
            if kind == "mixed":
                # [k, S] tokens + validity: a lane emits at most one
                # token per step, valid only on decode steps and the
                # completion step's first token
                rows = vals[off:off + k * S].reshape(k, S)
                off += k * S
                ok = vals[off:off + k * S].reshape(k, S) != 0
                off += k * S
                for slot, (req, gen) in snapshot.items():
                    if req.done or req.slot != slot \
                            or req.preemptions != gen:
                        continue
                    for tok, good in zip(rows[:, slot].tolist(),
                                         ok[:, slot].tolist()):
                        if not good:
                            continue
                        self._next_tokens[slot] = tok
                        self._emit(req, tok)
                        if req.done:
                            break
                continue
            rows = vals[off:off + k * S].reshape(k, S)
            off += k * S
            for slot, (req, gen) in snapshot.items():
                if req.done or req.slot != slot or req.preemptions != gen:
                    continue
                # ONE vectorized column slice + bulk int conversion per
                # live slot instead of k per-element int(row[slot])
                # casts over the whole [k, S] block (O(k*S) Python work
                # per drain at S=32, k=16)
                for tok in rows[:, slot].tolist():
                    self._next_tokens[slot] = tok
                    self._emit(req, tok)
                    if req.done:
                        break
        self._epoch += 1  # outputs / pending-first changed
        return self._c_finished.value > finished_before

    def _mixed_transitions(self, pf_slots, snapshot: Dict) -> None:
        """Drain-time completion transitions for a mixed block's
        prefill lanes: members whose prompt finished inside the block
        (the dispatch-time host simulation recorded the set) leave the
        prefill group and start decoding. Pages publish for prefix
        reuse exactly where the alternating path's _finish_prefill did
        it — after a point where every staged K/V byte is flushed
        (this drain flushed the window first). The generation check
        skips members cancelled or preempted since dispatch."""
        for slot in pf_slots:
            entry = snapshot.get(slot)
            if entry is None:
                continue
            req, gen = entry
            if req.done or req.slot != slot or req.preemptions != gen:
                continue
            if req.state != "prefilling":
                continue  # an earlier drained block already transitioned
            self.alloc.register(slot, req.all_tokens)
            self._prefill_group.remove(req)
            req.state = "running"
            self.running.append(req)
            ran = len(req.all_tokens) - req.cached_at_admit
            self._h_prefill_tokens.observe(ran)
            if self.trace is not None:
                self.trace.event(req.id, "prefill_done", tokens=ran,
                                 total=len(req.all_tokens))
            self._epoch += 1

    def _emit_spec(self, toks3: np.ndarray, valid3: np.ndarray,
                   snapshot: Dict) -> None:
        """Emit one drained spec block: toks3/valid3 [R, S, C] hold
        each round's emissions per slot (valid marks the real ones —
        device-truncated at stop/budget). Host emission walks rounds in
        dispatch order per live slot, re-truncating via _emit's done
        check as a backstop; per-round acceptance feeds the spec
        instruments (a round's emissions are 1 correction/bonus plus
        `count-1` accepted drafts)."""
        R = toks3.shape[0]
        # per-round acceptance ceiling: gamma accepted drafts for the
        # linear chain, tree depth D = emit_width - 1 for tree mode
        # (the root->leaf walk accepts at most one node per depth)
        denom = self.engine.spec_emit_width - 1
        # verify forwards that did work: rounds with ANY valid emission
        # (trailing all-dead rounds in a block ran but verified nothing)
        self._c_spec_fwd.inc(int(np.any(valid3, axis=(1, 2)).sum()))
        for slot, (req, gen) in snapshot.items():
            if req.done or req.slot != slot or req.preemptions != gen:
                continue
            t_rows = toks3[:, slot, :].tolist()
            v_rows = valid3[:, slot, :].tolist()
            for r in range(R):
                # mixed dispatch: a round that emits the request's very
                # first token is the prefill-completion round, not a
                # verify round — it must not count as a zero-acceptance
                # observation (the alternating path's first token never
                # passes through here either)
                first_round = req.t_first_token is None
                cnt = 0
                for tok, ok in zip(t_rows[r], v_rows[r]):
                    if not ok:
                        continue
                    cnt += 1
                    self._next_tokens[slot] = tok
                    self._emit(req, tok)
                    if req.done:
                        break
                if cnt and not first_round:
                    self._c_spec_tok.inc(cnt)
                    self._c_spec_acc.inc(max(0, cnt - 1))
                    if req.speculative and denom > 0:
                        self._h_accept.observe((cnt - 1) / denom)
                if req.done:
                    break

    def _emit(self, req: Request, token: int) -> None:
        """Record one generated token; finish/stop bookkeeping."""
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
            self._ttfts.append(req.ttft)
            self._h_ttft.observe(req.ttft)
            if self.slo_ttft_s is not None:
                if req.ttft <= self.slo_ttft_s:
                    self._c_slo_ttft_ok.inc()
                else:
                    self._c_slo_viol.labels("ttft").inc()
            if self.trace is not None:
                self.trace.event(req.id, "first_token", ttft_s=req.ttft)
        else:
            self._itls.append(now - req.t_last_token)
        req.t_last_token = now
        req.output.append(token)
        self._c_tokens.inc()
        if req.on_token is not None:
            req.on_token(req, token)
        hit_stop = req.stop_token >= 0 and token == req.stop_token
        if hit_stop or len(req.output) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: Request, state: str = "finished") -> None:
        self._epoch += 1  # batch membership changes below
        mean_gap = None
        if state == "finished" and len(req.output) > 1 and \
                req.t_first_token is not None:
            mean_gap = ((req.t_last_token - req.t_first_token)
                        / (len(req.output) - 1))
            self._itl_means.append(mean_gap)
            self._h_itl_mean.observe(mean_gap)
        slo_ok = None
        if state == "finished" and (self.slo_ttft_s is not None
                                    or self.slo_itl_s is not None):
            # per-request attainment: a request violates when ANY
            # declared objective is missed (an undelivered first token
            # counts against TTFT — the client never saw one in time)
            viol = False
            if self.slo_ttft_s is not None:
                viol |= req.ttft is None or req.ttft > self.slo_ttft_s
            if self.slo_itl_s is not None and mean_gap is not None:
                if mean_gap <= self.slo_itl_s:
                    self._c_slo_itl_ok.inc()
                else:
                    self._c_slo_viol.labels("itl").inc()
                    viol = True
            slo_ok = not viol
            self._slo_window.append(0.0 if slo_ok else 1.0)
            self._g_slo_burn.set(sum(self._slo_window)
                                 / len(self._slo_window))
        if req.slot is not None:
            # publish the written tokens' full pages before releasing
            # (the latest sampled token's K/V is never written — it
            # would have landed on the NEXT decode step)
            self.alloc.register(req.slot, req.all_tokens[:self._written(req)])
        req.state = state
        req.t_finish = time.monotonic()
        if req in self._prefill_group:  # cancelled mid-chunked-prefill
            self._prefill_group.remove(req)
        if req in self._sp_group:  # cancelled mid-seq-parallel-prefill
            self._sp_group.remove(req)
        if req.slot is not None:
            self.alloc.release(req.slot)
            self.engine.reset_slot(req.slot)
            # mixed carries: plen 0 marks the freed slot decode-phase
            # (a stale cursor then compares >= 0 and never re-enters
            # prefill); readmission reseeds both
            self._plen_host[req.slot] = 0
            self.slots[req.slot] = None
            req.slot = None
        if req in self.running:
            self.running.remove(req)
        if state == "finished":
            self._c_finished.inc()
        if self.trace is not None:
            attrs = {}
            if slo_ok is not None:
                attrs["slo_ok"] = slo_ok
            if mean_gap is not None:
                attrs["itl_mean_s"] = mean_gap
            self.trace.event(req.id, "finish", state=state,
                             tokens=len(req.output),
                             preemptions=req.preemptions,
                             ttft_s=req.ttft, **attrs)
        if req.on_finish is not None:
            req.on_finish(req)

    def _ensure_or_preempt(self, req: Request, need_len: int) -> None:
        """Grow req's pages; under pressure with work in flight, fall
        back to a FULL drain barrier (finishes surfaced there may free
        enough pages — and a victim's pages must never be reclaimed
        while a dispatched block still writes them); only then preempt
        the youngest live request (possibly req itself) until it fits —
        older requests always win page pressure. The victim pool
        includes partially-prefilled gang members: a young mid-prefill
        admission is the cheapest eviction (no generated tokens to
        recompute) and must not be able to starve an older decoding
        request of pages."""
        while True:
            if req.done or req.slot is None:
                return  # a drain barrier below finished/preempted req
            fresh = self.alloc.grow(req.slot, need_len)
            if fresh is not None:
                if fresh:  # push the grown block table to the device
                    self.engine.set_table_row(req.slot,
                                              self.alloc.pages_of(req.slot))
                return
            if self._inflight or self._pending_first:
                self._drain_inflight("page_pressure")
                continue
            # batch-class requests are preferred victims (shed-first
            # priority semantics); within a class the youngest loses —
            # so an old batch job still yields to a young interactive
            # one, but interactive never pays for batch's pages
            victim = max(self.running + self._prefill_group
                         + self._sp_group,
                         key=lambda r: (r.priority == "batch", r.t_arrive))
            self._preempt(victim)
            if victim is req:
                return

    def _written(self, req: Request) -> int:
        """Tokens whose K/V the device has actually written for req's
        slot: everything prefilled, plus decoded tokens except the last
        sampled one (written on the next step, which never ran).

        A running request whose device-sampled FIRST token has not yet
        drained (output still empty, entry in _pending_first) has every
        one of its all_tokens (= the whole prompt) written by prefill —
        the undrained first token is not in all_tokens, so there is no
        trailing unwritten sample to subtract (ADVICE.md r5: the old
        blanket -1 under-registered a full page at page boundaries)."""
        if req.state == "prefilling":
            return req.prefilled
        if not req.output and \
                (req.id, req.preemptions) in self._pending_first_keys:
            return len(req.all_tokens)
        return len(req.all_tokens) - 1

    def _preempt(self, req: Request) -> None:
        """Recompute-style preemption: free pages, requeue at the front.
        With prefix caching the pages stay warm in the registry, so
        readmission's "recompute" is usually a cache hit. The victim may
        be a partially-prefilled gang member (state "prefilling"): its
        prefilled-so-far pages register for reuse like any other and it
        restarts its prompt on readmission."""
        self._epoch += 1  # batch membership changes below
        self._c_preempt.inc()
        if self.flightrec is not None:
            self.flightrec.note("preempt", id=req.id, slot=req.slot,
                                priority=req.priority,
                                generated=len(req.output))
        if self.trace is not None:
            self.trace.event(req.id, "preempt", slot=req.slot,
                             state=req.state,
                             preemptions=req.preemptions + 1,
                             prefilled=req.prefilled,
                             generated=len(req.output))
        # register BEFORE bumping the generation: _written's pending-
        # first-token check matches entries queued under the current one
        self.alloc.register(req.slot, req.all_tokens[:self._written(req)])
        req.preemptions += 1
        self.alloc.release(req.slot)
        self.engine.reset_slot(req.slot)
        self._plen_host[req.slot] = 0  # mixed carries: decode-phase
        self.slots[req.slot] = None
        req.slot = None
        if req in self.running:
            self.running.remove(req)
        elif req in self._prefill_group:
            self._prefill_group.remove(req)
        else:
            self._sp_group.remove(req)
        # all_tokens (prompt + output) are recomputed on readmission
        req.state = "waiting"
        req.prefilled = 0
        req.t_enqueued = time.monotonic()
        self.waiting.appendleft(req)
