"""Workload modeling: stochastic mixed traffic for honest serving numbers.

Every serving number before ISSUE 10 was earned against uniform 128/128
closed-loop traffic (`serving_preemptions: 0` in BENCH_r05) — chunked
prefill, bucketing, preemption, the prefix cache, and the PR-8 admission
machinery were unmeasured exactly where real traffic hits them.
Production traces show heterogeneous prompt/decode lengths and bursty
arrivals (Patel et al., "Splitwise", arXiv:2311.18677), and
continuous-batching systems are evaluated on length-mixed stochastic
workloads (Kwon et al., vLLM, arXiv:2309.06180). This package is that
substrate:

  models.py    composable request-population specs (length
               distributions, shared-prefix cohorts, priority/deadline
               mix) with seeded, reproducible trace generation
  arrivals.py  open-loop arrival processes (Poisson, bursty Markov-
               modulated on/off, ramp-to-saturation) — load is no
               longer bounded by closed-loop client count
  replay.py    JSONL trace serialization + an absolute-time replay
               driver over a live server/router/control-plane URL
               (reuses tools/loadgen.py's request/judging machinery)
  sweep.py     operating-point sweep engine: one workload across a
               decode_steps_per_tick x inflight_blocks grid, emitting
               the latency/throughput curve + knee point

models/arrivals/replay are stdlib-only (no jax, no numpy) so traces can
be generated and replayed from any host; sweep drives an in-process
Scheduler and imports the engine lazily.
"""
from butterfly_tpu.workload.arrivals import (  # noqa: F401
    MarkovOnOff,
    Poisson,
    Ramp,
    assign_arrivals,
    parse_arrival,
)
from butterfly_tpu.workload.models import (  # noqa: F401
    WORKLOADS,
    Cohort,
    LogNormal,
    RequestSpec,
    Uniform,
    Workload,
    get_workload,
    mixed_chat,
)
