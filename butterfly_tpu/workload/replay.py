"""Trace serialization (JSONL) + absolute-time open-loop replay.

A trace is one JSONL file: a header line (trace kind/version, the
workload spec that generated it, the arrival spec, the seed), then one
line per request (`RequestSpec.to_json`, sorted keys). Serialization is
deterministic: the same workload spec + seed writes byte-identical
files, and load -> save round-trips byte-identically — the property
that makes a saved trace a *citable benchmark input* instead of a
one-off (pinned in tests/test_workload.py).

`replay_trace` fires a trace at a live server/router/control-plane URL
with **absolute-time fidelity**: request i is sent at
`t0 + arrival_s/speed` regardless of how earlier requests are faring
(open loop — a slow server gets a growing queue, exactly what the
admission machinery must be measured under). Request firing, judging,
and outcome accounting are tools/loadgen.py's (`fire_one` /
`Collector` — TTFT/ITL/SLO verdicts, terminal-outcome breakdown,
post-run /metrics scrape), reused rather than duplicated.

Trace IO is stdlib-only; replay needs only loadgen (urllib+threading).
"""
from __future__ import annotations

import importlib
import json
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from butterfly_tpu.workload.models import RequestSpec, Workload

TRACE_KIND = "butterfly-workload-trace"
TRACE_VERSION = 1


def _loadgen():
    """Import tools/loadgen.py (lives outside the package; same
    sys.path dance obs/benchmark.py uses)."""
    if "loadgen" in sys.modules:
        return sys.modules["loadgen"]
    tools = str(Path(__file__).resolve().parents[2] / "tools")
    sys.path.insert(0, tools)
    try:
        return importlib.import_module("loadgen")
    finally:
        sys.path.remove(tools)


# ---------------------------------------------------------------------------
# Trace files
# ---------------------------------------------------------------------------


def trace_text(specs: List[RequestSpec], *,
               workload: Optional[Workload] = None,
               arrival: Optional[str] = None,
               seed: Optional[int] = None) -> str:
    """Render a trace as JSONL text (header + one line per request).
    Key order is pinned (sort_keys) so equal traces are equal bytes."""
    header = {"kind": TRACE_KIND, "version": TRACE_VERSION,
              "n": len(specs)}
    if workload is not None:
        header["workload"] = workload.spec()
    if arrival is not None:
        header["arrival"] = arrival
    if seed is not None:
        header["seed"] = seed
    lines = [json.dumps(header, sort_keys=True)]
    lines += [json.dumps(s.to_json(), sort_keys=True) for s in specs]
    return "\n".join(lines) + "\n"


def save_trace(path, specs: List[RequestSpec], *,
               workload: Optional[Workload] = None,
               arrival: Optional[str] = None,
               seed: Optional[int] = None) -> None:
    Path(path).write_text(trace_text(specs, workload=workload,
                                     arrival=arrival, seed=seed))


def load_trace(path) -> Tuple[Dict, List[RequestSpec]]:
    """Read a trace file -> (header, specs). Raises ValueError on a
    file that isn't a butterfly workload trace (a stray JSONL fed to
    --trace should fail loudly, not replay garbage)."""
    lines = [ln for ln in Path(path).read_text().splitlines()
             if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty trace file")
    header = json.loads(lines[0])
    if not isinstance(header, dict) or header.get("kind") != TRACE_KIND:
        raise ValueError(f"{path}: not a {TRACE_KIND} file")
    specs = [RequestSpec.from_json(json.loads(ln)) for ln in lines[1:]]
    if header.get("n") is not None and int(header["n"]) != len(specs):
        raise ValueError(f"{path}: header says {header['n']} requests, "
                         f"file has {len(specs)}")
    return header, specs


# ---------------------------------------------------------------------------
# Replay driver
# ---------------------------------------------------------------------------


def replay_trace(url: str, specs: List[RequestSpec], *,
                 path: str = "/generate", timeout: float = 120.0,
                 speed: float = 1.0,
                 slo_ttft_ms: Optional[float] = None,
                 slo_itl_ms: Optional[float] = None,
                 scrape: bool = True) -> Dict:
    """Fire `specs` at `url` open-loop on their absolute schedule.

    One thread per request sleeps until its `arrival_s / speed` offset
    from the common start, then fires — each thread computes its delay
    from the shared t0, so schedule error never accumulates across
    requests (absolute-time fidelity, not cumulative gaps). `speed` > 1
    compresses the schedule (replay a 60 s trace in 6 s at speed=10).

    Returns the loadgen summary shape (outcomes/terminal breakdown,
    latency + TTFT percentiles, SLO attainment when objectives are
    declared) plus replay bookkeeping and — like every loadgen run —
    the target's post-run server-side counters under ``server`` so
    client-observed and server-counted outcomes sit in one artifact.
    """
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    lg = _loadgen()
    col = lg.Collector(slo_ttft_ms=slo_ttft_ms, slo_itl_ms=slo_itl_ms)
    t0 = time.monotonic()

    def fire(spec: RequestSpec) -> None:
        delay = spec.arrival_s / speed - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        lg.fire_one(url, path, spec.payload(), timeout, col,
                    label=f"trace-{spec.index}")

    threads = [threading.Thread(target=fire, args=(s,), daemon=True)
               for s in specs]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    out = col.summary(wall)
    out["open_loop"] = True
    out["replay_speed"] = speed
    out["offered_span_s"] = (max(s.arrival_s for s in specs) / speed
                             if specs else 0.0)
    if scrape:
        out["server"] = lg.scrape_server_counters(url, timeout=10.0)
    return out
