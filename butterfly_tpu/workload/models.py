"""Request-population specs: what a workload's traffic looks like.

A `Workload` is a weighted mix of `Cohort`s; each cohort draws its
prompt length and decode budget from a composable distribution
(`Uniform` / `LogNormal` / `Buckets` — the bucketed-empirical form fits
measured production histograms) and may share a **page-aligned prefix**
with every other request of its cohort (the "same chat template /
system prompt" population). Shared prefixes are sized in whole KV pages
so they register and hash as complete `chain_block_hashes` blocks
(cache/prefix.py) — the same alignment tools/loadgen.py's
`shared_prefix` uses — which is what lets the prefix cache and the
router's affinity ring actually see the sharing.

`Workload.sample(n, seed)` is deterministic and **insertion-order
independent**: every request draws from its own seeded stream
(`Random(seed, index)`), so the same spec + seed yields a byte-identical
trace regardless of how the caller slices or extends it, and a
mutation anywhere in one request's draw chain cannot shift every later
request (the property the determinism tests pin).

stdlib-only: generating a trace needs no jax, no numpy, no backend.
"""
from __future__ import annotations

import hashlib
import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

# ---------------------------------------------------------------------------
# Length distributions
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Uniform:
    """Integer uniform on [lo, hi] inclusive."""
    lo: int
    hi: int

    def sample(self, rng: random.Random) -> int:
        return rng.randint(self.lo, self.hi)

    def spec(self) -> Dict:
        return {"dist": "uniform", "lo": self.lo, "hi": self.hi}


@dataclass(frozen=True)
class LogNormal:
    """Lognormal with a given median, clamped to [lo, hi].

    The natural shape for prompt/response lengths: most requests are
    short, a heavy tail is long (Splitwise, arXiv:2311.18677 fig. 3).
    `sigma` is the log-space standard deviation (0.7 ~ a 2x spread
    around the median per sigma).
    """
    median: float
    sigma: float
    lo: int
    hi: int

    def sample(self, rng: random.Random) -> int:
        v = self.median * math.exp(rng.gauss(0.0, self.sigma))
        return max(self.lo, min(self.hi, int(round(v))))

    def spec(self) -> Dict:
        return {"dist": "lognormal", "median": self.median,
                "sigma": self.sigma, "lo": self.lo, "hi": self.hi}


@dataclass(frozen=True)
class Buckets:
    """Bucketed-empirical: weighted (lo, hi, weight) ranges.

    Fit a measured histogram directly: pick a bucket by weight, then
    uniform within it. The tuple-of-tuples form keeps the spec hashable
    (frozen dataclasses are jit-static-friendly and dict-key-safe).
    """
    buckets: Tuple[Tuple[int, int, float], ...]

    def sample(self, rng: random.Random) -> int:
        total = sum(w for _, _, w in self.buckets)
        x = rng.random() * total
        for lo, hi, w in self.buckets:
            x -= w
            if x <= 0:
                return rng.randint(lo, hi)
        lo, hi, _ = self.buckets[-1]
        return rng.randint(lo, hi)

    def spec(self) -> Dict:
        return {"dist": "buckets",
                "buckets": [list(b) for b in self.buckets]}


Dist = Union[Uniform, LogNormal, Buckets]


def dist_from_spec(spec: Dict) -> Dist:
    kind = spec.get("dist")
    if kind == "uniform":
        return Uniform(int(spec["lo"]), int(spec["hi"]))
    if kind == "lognormal":
        return LogNormal(float(spec["median"]), float(spec["sigma"]),
                         int(spec["lo"]), int(spec["hi"]))
    if kind == "buckets":
        return Buckets(tuple((int(lo), int(hi), float(w))
                             for lo, hi, w in spec["buckets"]))
    raise ValueError(f"unknown distribution spec {spec!r}")


# ---------------------------------------------------------------------------
# Cohorts and workloads
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Cohort:
    """One request population inside a workload.

    `shared_prefix_pages` > 0 gives every request of this cohort the
    same leading token block, sized in whole KV pages so the prefix
    registers/hashes as complete chain blocks (the chat-template
    population the prefix cache and router affinity exist for). The
    prefix tokens are derived from (workload name, cohort name) alone —
    NOT the sample seed — so traces sampled with different seeds still
    present one stable prefix population to a cache.
    """
    name: str
    weight: float
    prompt_len: Dist
    max_new: Dist
    shared_prefix_pages: int = 0
    temperature: float = 0.0
    priority: str = "interactive"
    deadline_ms: Optional[float] = None
    speculative: bool = True

    def spec(self) -> Dict:
        return {"name": self.name, "weight": self.weight,
                "prompt_len": self.prompt_len.spec(),
                "max_new": self.max_new.spec(),
                "shared_prefix_pages": self.shared_prefix_pages,
                "temperature": self.temperature,
                "priority": self.priority,
                "deadline_ms": self.deadline_ms,
                "speculative": self.speculative}


@dataclass
class RequestSpec:
    """One sampled request of a trace (the unit replay fires)."""
    index: int
    cohort: str
    tokens: List[int]
    max_new: int
    temperature: float = 0.0
    priority: str = "interactive"
    deadline_ms: Optional[float] = None
    speculative: bool = True
    arrival_s: float = 0.0  # offset from trace start (arrivals.py)

    def payload(self) -> Dict:
        """The /generate request body this spec stands for."""
        body: Dict = {"tokens": list(self.tokens),
                      "max_tokens": self.max_new,
                      "stop_token": -1,
                      "request_id": f"trace-{self.index}"}
        if self.temperature:
            body["temperature"] = self.temperature
        if self.priority != "interactive":
            body["priority"] = self.priority
        if self.deadline_ms is not None:
            body["deadline_ms"] = self.deadline_ms
        if not self.speculative:
            body["speculative"] = False
        return body

    def to_json(self) -> Dict:
        return {"index": self.index, "cohort": self.cohort,
                "tokens": list(self.tokens), "max_new": self.max_new,
                "temperature": self.temperature,
                "priority": self.priority,
                "deadline_ms": self.deadline_ms,
                "speculative": self.speculative,
                "arrival_s": self.arrival_s}

    @classmethod
    def from_json(cls, obj: Dict) -> "RequestSpec":
        return cls(index=int(obj["index"]), cohort=str(obj["cohort"]),
                   tokens=[int(t) for t in obj["tokens"]],
                   max_new=int(obj["max_new"]),
                   temperature=float(obj.get("temperature", 0.0)),
                   priority=str(obj.get("priority", "interactive")),
                   deadline_ms=(None if obj.get("deadline_ms") is None
                                else float(obj["deadline_ms"])),
                   speculative=bool(obj.get("speculative", True)),
                   arrival_s=float(obj.get("arrival_s", 0.0)))


def _stream(seed: int, *parts) -> random.Random:
    """An independent deterministic substream: SHA-256 over (seed,
    parts) -> Random seed. Substreams never share state, so one
    request's draw count can't shift another's values (and Python's
    Mersenne seeding from a big int is version-stable)."""
    h = hashlib.sha256(("%d|" % seed + "|".join(str(p) for p in parts))
                       .encode()).digest()
    return random.Random(int.from_bytes(h[:8], "big"))


@dataclass(frozen=True)
class Workload:
    """A named, weighted mix of cohorts over a token-id vocabulary."""
    name: str
    cohorts: Tuple[Cohort, ...]
    vocab: int = 258            # tiny-model/ByteTokenizer default
    page_size: int = 16         # prefix alignment unit (match the server)

    def __post_init__(self):
        if not self.cohorts:
            raise ValueError("workload needs at least one cohort")
        for c in self.cohorts:
            if c.weight <= 0:
                raise ValueError(f"cohort {c.name!r} weight must be > 0")
            if c.priority not in ("interactive", "batch"):
                raise ValueError(f"cohort {c.name!r}: unknown priority "
                                 f"{c.priority!r}")

    def prefix_tokens(self, cohort: Cohort) -> List[int]:
        """The cohort's shared leading block: page-aligned length, token
        ids derived from (workload, cohort) names only — stable across
        sample seeds, so every trace of this workload shares it."""
        n = cohort.shared_prefix_pages * self.page_size
        if n <= 0:
            return []
        rng = _stream(0, "prefix", self.name, cohort.name,
                      self.vocab, self.page_size)
        return [rng.randrange(1, self.vocab) for _ in range(n)]

    def sample(self, n: int, seed: int = 0) -> List[RequestSpec]:
        """Generate `n` request specs, deterministically.

        Prompt length is max(sampled, prefix + 1): a cohort's shared
        prefix is always followed by at least one private token, so
        last-token logits never come off a shared page."""
        cum: List[Tuple[float, Cohort]] = []
        acc = 0.0
        for c in self.cohorts:
            acc += c.weight
            cum.append((acc, c))
        total = acc
        prefixes = {c.name: self.prefix_tokens(c) for c in self.cohorts}
        specs: List[RequestSpec] = []
        for i in range(n):
            rng = _stream(seed, "req", self.name, i)
            x = rng.random() * total
            cohort = next(c for hi, c in cum if x <= hi)
            prefix = prefixes[cohort.name]
            plen = max(cohort.prompt_len.sample(rng), len(prefix) + 1)
            tail = [rng.randrange(1, self.vocab)
                    for _ in range(plen - len(prefix))]
            specs.append(RequestSpec(
                index=i, cohort=cohort.name, tokens=prefix + tail,
                max_new=cohort.max_new.sample(rng),
                temperature=cohort.temperature,
                priority=cohort.priority,
                deadline_ms=cohort.deadline_ms,
                speculative=cohort.speculative))
        return specs

    @property
    def max_prompt_len(self) -> int:
        """Upper bound on sampled prompt length (pool-sizing aid)."""
        out = 0
        for c in self.cohorts:
            hi = c.prompt_len.hi if not isinstance(c.prompt_len, Buckets) \
                else max(b[1] for b in c.prompt_len.buckets)
            out = max(out, hi, c.shared_prefix_pages * self.page_size + 1)
        return out

    @property
    def max_new_hi(self) -> int:
        out = 0
        for c in self.cohorts:
            hi = c.max_new.hi if not isinstance(c.max_new, Buckets) \
                else max(b[1] for b in c.max_new.buckets)
            out = max(out, hi)
        return out

    def spec(self) -> Dict:
        return {"name": self.name, "vocab": self.vocab,
                "page_size": self.page_size,
                "cohorts": [c.spec() for c in self.cohorts]}

    @classmethod
    def from_spec(cls, spec: Dict) -> "Workload":
        return cls(name=str(spec["name"]),
                   vocab=int(spec.get("vocab", 258)),
                   page_size=int(spec.get("page_size", 16)),
                   cohorts=tuple(Cohort(
                       name=str(c["name"]), weight=float(c["weight"]),
                       prompt_len=dist_from_spec(c["prompt_len"]),
                       max_new=dist_from_spec(c["max_new"]),
                       shared_prefix_pages=int(
                           c.get("shared_prefix_pages", 0)),
                       temperature=float(c.get("temperature", 0.0)),
                       priority=str(c.get("priority", "interactive")),
                       deadline_ms=(None if c.get("deadline_ms") is None
                                    else float(c["deadline_ms"])),
                       speculative=bool(c.get("speculative", True)))
                       for c in spec["cohorts"]))


# ---------------------------------------------------------------------------
# Canned workloads
# ---------------------------------------------------------------------------


def mixed_chat(*, page_size: int = 16, vocab: int = 258,
               prompt_lo: int = 32, prompt_hi: int = 1024,
               max_new_lo: int = 8, max_new_hi: int = 256,
               deadline_ms: Optional[float] = None) -> Workload:
    """The canned preemption-forcing mixed workload (ISSUE 10).

    Five cohorts modeling a chat service's production mix:

    * ``chat`` (45%) — the main interactive population: two shared
      template pages (system prompt), lognormal prompts/responses.
    * ``chat_alt`` (20%) — a second template cohort (different shared
      prefix), shorter prompts: two prefix populations is the minimum
      that exercises affinity *splitting* rather than one hot arc.
    * ``doc_batch`` (20%) — batch-priority long-prompt/short-answer
      summarization: the shed-first, preempt-first class.
    * ``probe`` (15%) — short interactive probes; carries the
      workload's deadline budget when one is declared.
    * ``long_doc`` (10%, ISSUE 13) — the top of the prompt range
      (prompt_hi/2..prompt_hi; 512-1024 at the TPU sizing), batch
      priority, near-minimal decode budget: prompts that exceed
      prefill_chunk and so CHUNK across scheduler ticks, putting the
      warm-prefix prefill path (flash cached-prefix kernel vs dense
      fallback) under the mixed bench's clock — ROADMAP item 5's
      long-doc cohort. Under a seq-parallel mesh with
      ``seq_parallel_threshold`` below prompt_hi (ISSUE 20), the
      cohort's longest prompts additionally route through the
      scheduler's seq-parallel prefill lane, so the mixed bench
      exercises the lane's chunk dispatches against live decode
      traffic (the dedicated ``longctx_*`` bench row measures that
      interference in isolation).

    Prompt lengths span [prompt_lo, prompt_hi] (default 32-1024),
    decode budgets [max_new_lo, max_new_hi] — heterogeneous enough
    that page demand is bursty and slot lifetimes interleave, which
    (with a pool sized below worst-case demand) is what drives
    preemption, shedding, and deadline scrubbing instead of the
    uniform 128/128 best case.
    """
    mid_prompt = max(prompt_lo + 1, min(prompt_hi, 3 * prompt_lo))
    mid_new = max(max_new_lo + 1, min(max_new_hi,
                                      (max_new_lo + max_new_hi) // 3))
    prefix_pages = max(1, min(2, (prompt_lo - 1) // page_size))
    return Workload(
        name="mixed_chat", vocab=vocab, page_size=page_size,
        cohorts=(
            Cohort("chat", 0.45,
                   LogNormal(mid_prompt, 0.7, prompt_lo, prompt_hi),
                   LogNormal(mid_new, 0.6, max_new_lo, max_new_hi),
                   shared_prefix_pages=prefix_pages),
            Cohort("chat_alt", 0.20,
                   LogNormal(max(prompt_lo + 1, 2 * prompt_lo), 0.5,
                             prompt_lo, prompt_hi),
                   Uniform(max_new_lo, max(max_new_lo, max_new_hi // 2)),
                   shared_prefix_pages=prefix_pages),
            Cohort("doc_batch", 0.20,
                   Uniform(max(prompt_lo, prompt_hi // 2), prompt_hi),
                   Uniform(max_new_lo, max(max_new_lo, max_new_hi // 4)),
                   priority="batch"),
            Cohort("probe", 0.15,
                   Uniform(prompt_lo, min(prompt_hi, 2 * prompt_lo)),
                   Uniform(max_new_lo, max(max_new_lo, max_new_hi // 2)),
                   deadline_ms=deadline_ms),
            Cohort("long_doc", 0.10,
                   Uniform(max(prompt_lo, prompt_hi // 2), prompt_hi),
                   Uniform(max_new_lo, max(max_new_lo, max_new_hi // 8)),
                   priority="batch"),
        ))


def uniform(*, page_size: int = 16, vocab: int = 258,
            prompt_lo: int = 128, prompt_hi: Optional[int] = None,
            max_new_lo: int = 128, max_new_hi: Optional[int] = None,
            deadline_ms: Optional[float] = None) -> Workload:
    """The legacy best-case shape (every request identical when hi is
    left at lo) as a named workload, so sweeps can compare mixed vs
    uniform on one substrate. Same kwarg surface as mixed_chat so the
    CLI sizing flags apply to either."""
    return Workload(
        name="uniform", vocab=vocab, page_size=page_size,
        cohorts=(Cohort("uniform", 1.0,
                        Uniform(prompt_lo, prompt_hi or prompt_lo),
                        Uniform(max_new_lo, max_new_hi or max_new_lo),
                        deadline_ms=deadline_ms),))


WORKLOADS = {"mixed_chat": mixed_chat, "uniform": uniform}


def get_workload(name: str, **overrides) -> Workload:
    """Resolve a canned workload by name with sizing overrides."""
    try:
        factory = WORKLOADS[name]
    except KeyError:
        raise ValueError(f"unknown workload {name!r}: expected one of "
                         f"{sorted(WORKLOADS)}") from None
    return factory(**overrides)
