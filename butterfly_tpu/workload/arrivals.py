"""Open-loop arrival processes: offered load the server can't gate.

A closed-loop client (tools/loadgen.py's default) keeps one request in
flight — the server's own latency throttles the offered rate, so queue
growth, shedding, and preemption can never really be forced. These
processes generate **absolute arrival times** independent of service
progress (open loop), the regime where admission control and the page
pool actually get tested:

  Poisson(rate)                memoryless steady offered load
  MarkovOnOff(rate_on, ...)    bursty: ON phases at a high rate
                               alternate with quiet OFF phases
                               (Markov-modulated Poisson — the classic
                               bursty-traffic model; production arrival
                               traces are bursty, Splitwise §3)
  Ramp(rate0, rate1, ramp_s)   linearly ramp the offered rate — the
                               find-the-saturation-point sweep shape

Every process is deterministic given (spec, seed): `times(n, seed)`
returns n ascending arrival offsets (seconds from trace start). All
stdlib (`random.Random`), no numpy.

String specs (CLI / bench / trace headers) parse via `parse_arrival`:

    poisson:8            8 req/s Poisson
    burst:20:0.5:2       ON at 20 req/s for ~0.5s, OFF ~2s (rate 0)
    burst:20:0.5:2:1     ... with a 1 req/s trickle while OFF
    ramp:2:50:10         2 -> 50 req/s over 10s, then hold 50
"""
from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import List, Optional


def _rng(seed: int) -> random.Random:
    # decorrelate from workload sampling streams (models._stream hashes;
    # arrival processes just offset into a distinct constant)
    return random.Random((seed << 1) ^ 0xA55A5AA5)


@dataclass(frozen=True)
class Poisson:
    """Memoryless arrivals at `rate` requests/second."""
    rate: float

    def __post_init__(self):
        if self.rate <= 0:
            raise ValueError(f"poisson rate must be > 0, got {self.rate}")

    def times(self, n: int, seed: int = 0) -> List[float]:
        rng = _rng(seed)
        t, out = 0.0, []
        for _ in range(n):
            dt = rng.expovariate(self.rate)
            t += dt
            out.append(t)
        return out

    def spec(self) -> str:
        return f"poisson:{self.rate:g}"


@dataclass(frozen=True)
class MarkovOnOff:
    """Markov-modulated on/off bursts.

    Exponentially-distributed ON phases (mean `mean_on_s`) emit
    arrivals at `rate_on`; OFF phases (mean `mean_off_s`) at `rate_off`
    (default 0 = silent). Mean offered rate is
    rate_on*p_on + rate_off*(1-p_on) with p_on = on/(on+off), but the
    *instantaneous* rate during a burst is what overruns a page pool
    sized for the mean — the preemption-forcing property the mixed
    bench leans on.
    """
    rate_on: float
    mean_on_s: float
    mean_off_s: float
    rate_off: float = 0.0

    def __post_init__(self):
        if self.rate_on <= 0 or self.mean_on_s <= 0 or self.mean_off_s <= 0:
            raise ValueError("burst rate_on/mean_on_s/mean_off_s must "
                             "be > 0")
        if self.rate_off < 0:
            raise ValueError("burst rate_off must be >= 0")

    def times(self, n: int, seed: int = 0) -> List[float]:
        rng = _rng(seed)
        t, out = 0.0, []
        on = True
        while len(out) < n:
            rate = self.rate_on if on else self.rate_off
            mean = self.mean_on_s if on else self.mean_off_s
            phase_end = t + rng.expovariate(1.0 / mean)
            while len(out) < n and rate > 0:
                gap = rng.expovariate(rate)
                if t + gap > phase_end:
                    break
                t += gap
                out.append(t)
            t = phase_end
            on = not on
        return out

    def spec(self) -> str:
        s = (f"burst:{self.rate_on:g}:{self.mean_on_s:g}"
             f":{self.mean_off_s:g}")
        return s + (f":{self.rate_off:g}" if self.rate_off else "")


@dataclass(frozen=True)
class Ramp:
    """Linear rate ramp rate0 -> rate1 over `ramp_s` seconds, holding
    rate1 after — the ramp-to-saturation shape. Sampled exactly by
    inverting the cumulative intensity Lambda(t) at unit-rate
    exponential marks (inhomogeneous-Poisson inversion, no thinning)."""
    rate0: float
    rate1: float
    ramp_s: float

    def __post_init__(self):
        if self.rate0 < 0 or self.rate1 <= 0 or self.ramp_s <= 0:
            raise ValueError("ramp needs rate0 >= 0, rate1 > 0, "
                             "ramp_s > 0")

    def _invert(self, s: float) -> float:
        """t such that Lambda(t) = s."""
        a = (self.rate1 - self.rate0) / (2.0 * self.ramp_s)
        s_ramp = self.rate0 * self.ramp_s + a * self.ramp_s ** 2
        if s <= s_ramp:
            if abs(a) < 1e-12:  # flat "ramp"
                return s / max(self.rate0, 1e-12)
            # solve a t^2 + rate0 t - s = 0 for the positive root
            return ((-self.rate0
                     + math.sqrt(self.rate0 ** 2 + 4.0 * a * s))
                    / (2.0 * a))
        return self.ramp_s + (s - s_ramp) / self.rate1

    def times(self, n: int, seed: int = 0) -> List[float]:
        rng = _rng(seed)
        s, out = 0.0, []
        for _ in range(n):
            s += rng.expovariate(1.0)
            out.append(self._invert(s))
        return out

    def spec(self) -> str:
        return f"ramp:{self.rate0:g}:{self.rate1:g}:{self.ramp_s:g}"


def parse_arrival(spec: str):
    """Parse an arrival-process string spec (see module docstring)."""
    parts = str(spec).split(":")
    kind, args = parts[0], parts[1:]
    try:
        if kind == "poisson" and len(args) == 1:
            return Poisson(float(args[0]))
        if kind == "burst" and len(args) in (3, 4):
            return MarkovOnOff(float(args[0]), float(args[1]),
                               float(args[2]),
                               float(args[3]) if len(args) == 4 else 0.0)
        if kind == "ramp" and len(args) == 3:
            return Ramp(float(args[0]), float(args[1]), float(args[2]))
    except ValueError as e:
        # re-raise numeric/validation errors with the spec attached
        raise ValueError(f"bad arrival spec {spec!r}: {e}") from None
    raise ValueError(
        f"unknown arrival spec {spec!r}: expected poisson:<rate>, "
        "burst:<rate_on>:<mean_on_s>:<mean_off_s>[:<rate_off>], or "
        "ramp:<rate0>:<rate1>:<ramp_s>")


def assign_arrivals(specs, process, seed: int = 0):
    """Stamp `arrival_s` on each RequestSpec in index order from the
    process's deterministic schedule. Returns `specs` (mutated)."""
    ts = process.times(len(specs), seed)
    for s, t in zip(specs, ts):
        s.arrival_s = t
    return specs
