"""Operating-point sweeps: one workload, a grid of scheduler knobs.

The serving stack's two throughput/latency levers are
`decode_steps_per_tick` (fused block width — amortizes host overhead,
adds per-token burst latency) and `inflight_blocks` (dispatch-ahead
depth — overlaps host scheduling with device compute, adds one block of
drain latency per level). Neither has a universally right value; the
honest number is the CURVE. `sweep_operating_points` runs the SAME
sampled trace (same requests, same arrival schedule) at every grid
point and emits per-point throughput + latency percentiles plus a knee
point, so a bench round documents *where* it operates, not just one
cherry-picked coordinate.

`drive_open_loop` is the shared in-process driver: it submits a trace's
requests into a Scheduler on their absolute arrival schedule (open
loop), routing each arrival through the PR-8 admission surface
(`shed_decision` -> counted 429, `deadline_ms` -> scheduler deadline
scrub) — the same calls ServerState.submit makes, without the HTTP
layer. obs/benchmark.py's mixed phase uses it too.

All grid points share ONE ServingEngine (the per-k decode programs
cache on the engine, and `inflight_blocks` is purely scheduler-side),
so a 2x2 CPU-smoke sweep compiles the engine once plus one decode scan
per distinct k — not four engines. jax is only touched by the engine
the caller built; this module itself stays import-light.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from butterfly_tpu.workload.arrivals import assign_arrivals, parse_arrival
from butterfly_tpu.workload.models import RequestSpec, Workload


def parse_grid(spec: str) -> List[Tuple[int, int]]:
    """'1,4x1,2' -> [(1,1), (1,2), (4,1), (4,2)] — the
    decode_steps_per_tick x inflight_blocks grid for the CLI."""
    try:
        ds_s, infl_s = spec.split("x")
        ds = [int(v) for v in ds_s.split(",") if v]
        infl = [int(v) for v in infl_s.split(",") if v]
        if not ds or not infl or min(ds + infl) < 1:
            raise ValueError("empty axis or value < 1")
    except ValueError as e:
        raise ValueError(
            f"bad grid spec {spec!r} (expected 'k1,k2x d1,d2' e.g. "
            f"'1,4x1,2'): {e}") from None
    return [(d, i) for d in ds for i in infl]


def drive_open_loop(sched, specs: Sequence[RequestSpec], *,
                    max_seconds: float = 600.0) -> Dict:
    """Submit `specs` into `sched` on their absolute arrival schedule
    and tick until drained. Open loop: arrivals never wait for service.

    Each arrival goes through the PR-8 admission decisions exactly like
    ServerState.submit: `shed_decision(prompt_len, priority)` first
    (counted as a shed_429 outcome — needs the scheduler built with
    slo_ttft_s), then `submit(..., deadline_s=...)` when the spec
    carries a deadline budget (expiries surface as state="expired", the
    504 outcome). A request whose prompt+max_new exceeds the engine's
    max_seq has its budget clamped (and is skipped entirely if the
    prompt alone doesn't fit — counted, never silently dropped).
    """
    order = sorted(specs, key=lambda s: (s.arrival_s, s.index))
    max_seq = sched.engine.cache.max_seq
    reqs, i = [], 0
    shed = skipped = 0
    t0 = time.monotonic()
    while i < len(order) or sched.has_work:
        if time.monotonic() - t0 > max_seconds:
            raise RuntimeError(
                f"open-loop drive exceeded {max_seconds}s with "
                f"{len(order) - i} arrivals pending")
        now = time.monotonic() - t0
        while i < len(order) and order[i].arrival_s <= now:
            s = order[i]
            i += 1
            if len(s.tokens) + 1 > max_seq:
                skipped += 1
                continue
            retry_after = sched.shed_decision(len(s.tokens), s.priority)
            if retry_after is not None:
                shed += 1
                continue
            deadline_s = (time.monotonic() + s.deadline_ms / 1e3
                          if s.deadline_ms is not None else None)
            reqs.append(sched.submit(
                s.tokens,
                max_new_tokens=min(s.max_new, max_seq - len(s.tokens)),
                temperature=s.temperature, priority=s.priority,
                deadline_s=deadline_s, speculative=s.speculative))
        if sched.has_work:
            sched.tick()
        elif i < len(order):
            time.sleep(min(0.002, max(
                0.0, order[i].arrival_s - (time.monotonic() - t0))))
    wall = time.monotonic() - t0
    m = sched.metrics()
    finished = sum(1 for r in reqs if r.state == "finished")
    expired = sum(1 for r in reqs if r.state == "expired")
    stuck = [r.id for r in reqs if not r.done]
    if stuck:
        raise RuntimeError(f"open-loop drive left requests undrained "
                           f"(ids {stuck[:8]})")
    out = {
        "requests": len(order),
        "admitted": len(reqs),
        "ok": finished,
        "shed_429": shed,
        "expired_504": expired,
        "skipped_too_long": skipped,
        "wall_s": wall,
        "tokens": m["tokens_generated_total"],
        "tokens_per_sec": m["tokens_generated_total"] / max(wall, 1e-9),
        "preemptions": m["preemptions_total"],
        "deadline_expired_total": m["deadline_expired_total"],
        "shed_total": m["shed_total"],
    }
    for k in ("ttft_p50", "ttft_p95", "itl_req_mean_p50",
              "itl_req_mean_p95", "prefix_cache_hit_tokens"):
        if k in m:
            out[k] = m[k]
    return out


def find_knee(points: List[Dict], ttft_slack: float = 2.0) -> Optional[Dict]:
    """The operating point to run at: max throughput among points whose
    ttft_p95 stays within `ttft_slack` x the grid's best ttft_p95 (the
    classic latency/throughput knee — past it you buy tokens/sec with
    tail latency). Falls back to plain max throughput when every point
    busts the slack. Deterministic and documented so bench rounds can
    compare knees across rounds."""
    usable = [p for p in points if p.get("ttft_p95") is not None]
    if not usable:
        return None
    floor = min(p["ttft_p95"] for p in usable)
    eligible = [p for p in usable
                if p["ttft_p95"] <= ttft_slack * floor] or usable
    best = max(eligible, key=lambda p: p["tokens_per_sec"])
    return {"decode_steps_per_tick": best["decode_steps_per_tick"],
            "inflight_blocks": best["inflight_blocks"],
            "tokens_per_sec": best["tokens_per_sec"],
            "ttft_p95": best["ttft_p95"],
            "rule": f"max tokens/sec with ttft_p95 <= {ttft_slack:g}x "
                    f"grid minimum ({floor:.4g}s)"}


def sweep_operating_points(engine, base_rt, specs: Sequence[RequestSpec],
                           grid: Sequence[Tuple[int, int]], *,
                           slo_ttft_s: Optional[float] = None,
                           warm_max_new: int = 2,
                           max_seconds: float = 600.0,
                           ttft_slack: float = 2.0) -> Dict:
    """Run `specs` at every (decode_steps_per_tick, inflight_blocks)
    grid point on ONE shared engine; returns {"points", "knee"}.

    Per distinct k a warmup scheduler replays the trace's prompts with
    a tiny budget first, so the measured pass doesn't eat the XLA
    compiles for that block width (inflight depth compiles nothing —
    its warm ride-along is free). Each measured pass gets a FRESH
    Scheduler so counters and latency reservoirs start at zero.
    """
    from butterfly_tpu.sched.scheduler import Scheduler

    points: List[Dict] = []
    warmed: set = set()
    for d, infl in grid:
        engine.runtime = base_rt.replace(decode_steps_per_tick=d,
                                         inflight_blocks=infl)
        if d not in warmed:
            warm = Scheduler(engine)
            for s in specs:
                if len(s.tokens) + 1 <= engine.cache.max_seq:
                    warm.submit(s.tokens, max_new_tokens=warm_max_new,
                                temperature=s.temperature)
            warm.run_until_done(max_ticks=10 ** 6)
            warmed.add(d)
        sched = Scheduler(engine, slo_ttft_s=slo_ttft_s)
        res = drive_open_loop(sched, specs, max_seconds=max_seconds)
        points.append({"decode_steps_per_tick": d,
                       "inflight_blocks": infl,
                       **{k: (round(v, 4) if isinstance(v, float) else v)
                          for k, v in res.items()}})
    return {"points": points, "knee": find_knee(points, ttft_slack)}


def run_operating_point_sweep(model, params, *, workload: Workload,
                              arrival: str, n_requests: int,
                              grid: Sequence[Tuple[int, int]],
                              max_batch: int = 8,
                              num_pages: int = 0,
                              kv_quant: str = "none",
                              prefill_max_batch: int = 8,
                              prefix_caching: bool = True,
                              slo_ttft_ms: Optional[float] = None,
                              seed: int = 0,
                              max_seconds: float = 600.0) -> Dict:
    """CLI/bench convenience: build the engine, sample + schedule the
    workload once, sweep the grid. max_seq is sized to the workload's
    own worst case so no request is clamped."""
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine.serving import ServingEngine

    specs = workload.sample(n_requests, seed)
    assign_arrivals(specs, parse_arrival(arrival), seed)
    max_seq = workload.max_prompt_len + workload.max_new_hi + 16
    base_rt = RuntimeConfig(max_batch_size=max_batch, max_seq_len=max_seq,
                            page_size=workload.page_size,
                            num_pages=num_pages, kv_quant=kv_quant,
                            prefill_max_batch=prefill_max_batch,
                            prefix_caching=prefix_caching)
    engine = ServingEngine(model, params, base_rt)
    out = sweep_operating_points(
        engine, base_rt, specs, grid,
        slo_ttft_s=slo_ttft_ms / 1e3 if slo_ttft_ms else None,
        max_seconds=max_seconds)
    out.update({"workload": workload.name, "arrival": arrival,
                "requests": n_requests, "seed": seed,
                "max_batch": max_batch, "kv_quant": kv_quant,
                "grid": [list(g) for g in grid]})
    return out
