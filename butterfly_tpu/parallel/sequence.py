"""Sequence/context parallelism: ring attention + Ulysses (long context).

The reference never mentions long-context mechanisms (SURVEY.md §5: absent
from all 6 files); this realizes the survey's required surface the TPU way:

* **Ring attention** (context parallel): Q/K/V are sequence-sharded over
  the `seq` mesh axis. Each of the N ring steps computes blockwise
  attention of the local Q chunk against the visiting K/V block, folded
  into an online-softmax accumulator (running max / denominator — the
  FlashAttention recurrence), then rotates K/V (+ their positions) to the
  next neighbor with `lax.ppermute`. On TPU the ring rides neighbor ICI
  links and XLA overlaps the permute with the block's einsums. Causality
  comes from comparing rotated K positions to local Q positions, so any
  chunk order works and no step is skipped (static schedule).

* **Ulysses**: `lax.all_to_all` reshards [B, T/N, H_all] -> [B, T, H/N]
  (heads scatter, sequence gathers), runs ordinary full attention on the
  now-complete local sequence for its head group, and reshards back.
  Requires num_kv_heads % N == 0; ring has no such constraint.

* **sp_forward**: whole-model long-context prefill under shard_map manual
  over {'seq'} — norms/MLP/MoE are token-pointwise (trivially sequence-
  parallel), attention uses ring or Ulysses; `tensor`/`data` axes remain
  GSPMD-auto inside, so SP composes with TP. Returns logits and the
  sequence-sharded KV cache (each device keeps the K/V it computed —
  that sharded layout IS the context-parallel cache).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import (
    KVCache, Params, attn_output, embed_tokens, ffn_block, final_logits,
    pre_norm, qkv_proj)

NEG = -1e30


def _block_scores(q, k, q_pos, k_pos, scale):
    """Masked f32 scores for one (local-Q, visiting-K) block pair.

    q: [B,Tq,Kv,G,H]; k: [B,Tk,Kv,H]; positions: [B,Tq]/[B,Tk].
    Returns [B,Kv,Tq,G,Tk]."""
    s = jnp.einsum("btkgh,bskh->bktgs", q, k,
                   preferred_element_type=jnp.float32) * scale
    causal = k_pos[:, None, :] <= q_pos[:, :, None]        # [B,Tq,Tk]
    return jnp.where(causal[:, None, :, None, :], s, NEG)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   q_pos: jax.Array, k_pos: jax.Array,
                   axis_name: str = "seq") -> jax.Array:
    """Causal GQA over a sequence ring (call inside shard_map).

    q: [B, Tq, Nq, H] local chunk; k/v: [B, Tk, Kv, H] local chunk;
    q_pos/k_pos: [B, T*] absolute positions. Returns [B, Tq, Nq, H].
    """
    B, Tq, Nq, H = q.shape
    Kv = k.shape[2]
    G = Nq // Kv
    N = lax.axis_size(axis_name)
    qg = q.reshape(B, Tq, Kv, G, H)
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    perm = [(i, (i + 1) % N) for i in range(N)]

    # online-softmax accumulators
    m = jnp.full((B, Kv, Tq, G), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, Kv, Tq, G), jnp.float32)
    acc = jnp.zeros((B, Kv, Tq, G, H), jnp.float32)

    def step(carry, _):
        m, l, acc, k, v, k_pos = carry
        s = _block_scores(qg, k, q_pos, k_pos, scale)      # [B,Kv,Tq,G,Tk]
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # fully-masked rows keep m=-inf; guard the exp shift
        shift = jnp.where(jnp.isinf(m_new), 0.0, m - m_new)
        p = jnp.exp(s - jnp.where(jnp.isinf(m_new), 0.0, m_new)[..., None])
        p = jnp.where(s <= NEG, 0.0, p)
        corr = jnp.exp(shift)
        l2 = l * corr + jnp.sum(p, axis=-1)
        acc2 = acc * corr[..., None] + jnp.einsum(
            "bktgs,bskh->bktgh", p, v.astype(jnp.float32))
        k, v, k_pos = lax.ppermute((k, v, k_pos), axis_name, perm)
        return (m_new, l2, acc2, k, v, k_pos), None

    (m, l, acc, _, _, _), _ = lax.scan(
        step, (m, l, acc, k, v, k_pos), None, length=N)
    out = acc / jnp.maximum(l, 1e-30)[..., None]       # [B,Kv,Tq,G,H]
    return out.transpose(0, 2, 1, 3, 4).reshape(B, Tq, Nq, H).astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      q_pos: jax.Array, axis_name: str = "seq") -> jax.Array:
    """All-to-all head<->sequence reshard + local full causal attention.

    q: [B, T/N, Nq, H]; k/v: [B, T/N, Kv, H]. Needs Nq % N == 0; when
    Kv < N (realistic GQA, e.g. Llama-3 Kv=8 on a 16-way seq axis) and
    N % Kv == 0, KV heads are REPLICATED r = N/Kv times before the
    all_to_all so device d receives the kv head (d // r) its q-head
    block contracts with — the seq axis is no longer capped at Kv, at
    the cost of r x the K/V all_to_all volume. Returns [B, T/N, Nq, H].
    """
    from butterfly_tpu.models.common import attend
    N = lax.axis_size(axis_name)
    B, Tl, Nq, H = q.shape
    Kv = k.shape[2]
    if Kv % N != 0:
        if N % Kv != 0 or Nq % N != 0:
            raise ValueError(
                f"ulysses needs Kv % N == 0 or (N % Kv == 0 and "
                f"Nq % N == 0); got Nq={Nq}, Kv={Kv}, N={N}")
        # head replication: q heads [d*Nq/N, (d+1)*Nq/N) all map to kv
        # head d // r (block size Nq/N divides the GQA group G = Nq/Kv
        # because Kv < N), so repeating each kv head r times puts the
        # right copy on every device after the head-scatter.
        r = N // Kv
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    # heads scatter (axis 2), sequence gathers (axis 1)
    qq = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kk = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vv = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # full global positions for the gathered sequence
    pos = lax.all_gather(q_pos, axis_name, axis=1, tiled=True)  # [B, T]
    mask = pos[:, None, :] <= pos[:, :, None]                   # [B,T,T]
    out = attend(qq, kk, vv, mask, None)  # attend() reads only shapes+mask
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


# ---------------------------------------------------------------------------
# Whole-model sequence-parallel prefill
# ---------------------------------------------------------------------------

def sp_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
               mesh: Mesh, impl: str = "ring"
               ) -> Tuple[jax.Array, KVCache]:
    """Long-context prefill with activations sharded over `seq`.

    tokens: [B, T] (T divisible by the seq axis). Returns
    (logits [B,T,V] seq-sharded on T, KVCache with S = T seq-sharded).
    """
    N = mesh.shape["seq"]
    B, T = tokens.shape
    if T % N != 0:
        raise ValueError(f"seq len {T} not divisible by seq axis {N}")

    body = partial(_sp_body, cfg=cfg, impl=impl)
    layer_in = jax.tree.map(lambda _: P(), params["layers"])
    head_in = jax.tree.map(lambda _: P(), {
        k: v for k, v in params.items() if k != "layers"})
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(layer_in, head_in, P(None, "seq")),
        out_specs=(P(None, "seq"), P(None, None, "seq")),
        axis_names={"seq"}, check_vma=False)
    logits, (ks, vs) = fn(params["layers"],
                          {k: v for k, v in params.items() if k != "layers"},
                          tokens)
    cache = KVCache(k=ks, v=vs,
                    length=jnp.full((B,), T, jnp.int32))
    return logits, cache


def sp_decode_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                   positions: jax.Array, prefix: KVCache, suffix: KVCache,
                   mesh: Mesh,
                   prefix_len: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, KVCache]:
    """One decode step consuming sp_forward's sequence-sharded cache.

    The long prefix stays sharded over `seq` exactly where prefill left it
    (never regathered); generated tokens live in a small replicated
    contiguous `suffix` cache. Attention is computed as one online-softmax
    merge (ring_attention's accumulator algebra): each device attends its
    local prefix chunk into partial (m, l, acc), the partials merge across
    the ring with pmax/psum — collectives sized [B,Nq,H], never [B,T,*] —
    and the suffix block folds in locally.

    tokens/positions: [B,1] (positions = prefix length + step).
    Returns (last-token logits [B,V], suffix cache with the new K/V).

    prefix_len [B]: number of REAL prefix tokens per row; prefix slots at
    or past it are masked out. Defaults to prefix.length (no padding).
    generate_long pads prompts up to a multiple of the seq axis, so the
    tail of the sharded prefix holds pad K/V that must not be attended.

    Capacity contract (as for the paged pool, where the host allocator
    guarantees pages): the caller must size the suffix cache for the
    whole decode run — a step past suffix.max_seq would clamp its write
    onto the last slot. Checked eagerly when lengths are concrete.
    """
    if not isinstance(suffix.length, jax.core.Tracer):
        if int(jnp.max(suffix.length)) >= suffix.max_seq:
            raise ValueError(
                f"suffix cache full ({suffix.max_seq} slots): size "
                "init_cache(max_seq=...) for the whole decode run")
    if prefix_len is None:
        prefix_len = prefix.length
    body = partial(_sp_decode_body, cfg=cfg)
    layer_in = jax.tree.map(lambda _: P(), params["layers"])
    head = {k: v for k, v in params.items() if k != "layers"}
    head_in = jax.tree.map(lambda _: P(), head)
    seq_kv = P(None, None, "seq")  # [L,B,T,Kv,H]: local T chunk per device
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(layer_in, head_in, P(), P(), seq_kv, seq_kv,
                  P(), P(), P(), P()),
        out_specs=(P(), P(), P()),
        axis_names={"seq"}, check_vma=False)
    logits, new_sk, new_sv = fn(params["layers"], head, tokens, positions,
                                prefix.k, prefix.v, suffix.k, suffix.v,
                                suffix.length, prefix_len)
    return logits, KVCache(new_sk, new_sv, suffix.length + 1)


def _sp_decode_body(layers, head, tokens, positions, pk, pv, sck, scv, slen,
                    plen, *, cfg: ModelConfig):
    """Per-device decode step (inside shard_map, manual over seq)."""
    from butterfly_tpu.models.common import update_cache_layer

    B = tokens.shape[0]
    Smax = sck.shape[2]
    x, cos, sin = embed_tokens(head, cfg, tokens, positions)
    compute_dtype = jnp.dtype(cfg.dtype)
    H = cfg.head_dim
    Kv = cfg.num_kv_heads
    G = cfg.num_heads // Kv
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    # suffix causal mask: slots 0..slen (inclusive of the token written
    # this step) are visible; everything prefix-side is older than the
    # query by construction, so the prefix needs no mask at all.
    j = jnp.arange(Smax)
    suf_mask = j[None, :] <= slen[:, None]                   # [B,Smax]
    # local prefix-chunk mask: global slot index < the row's REAL prefix
    # length (pad K/V past it — generate_long's divisibility padding —
    # must contribute nothing)
    idx = lax.axis_index("seq")
    Tl = pk.shape[2]
    gpos = idx * Tl + jnp.arange(Tl)                         # [Tl] global
    pre_mask = gpos[None, :] < plen[:, None]                 # [B,Tl]

    def layer(x, scanned):
        lp, pkl, pvl, ck, cv = scanned
        from butterfly_tpu.models.common import _cast_float
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)     # q [B,1,Nq,H]
        ck, cv = update_cache_layer(ck, cv, k, v, slen)
        qg = q.reshape(B, 1, Kv, G, H)

        # local prefix chunk -> partial online-softmax accumulators
        s_p = jnp.einsum("btkgh,bskh->bktgs", qg, pkl,
                         preferred_element_type=jnp.float32) * scale
        s_p = jnp.where(pre_mask[:, None, None, None, :], s_p, NEG)
        m_i = jnp.max(s_p, axis=-1)                          # [B,Kv,1,G]
        p_i = jnp.exp(s_p - m_i[..., None])
        p_i = jnp.where(s_p <= NEG, 0.0, p_i)
        l_i = jnp.sum(p_i, axis=-1)
        acc_i = jnp.einsum("bktgs,bskh->bktgh", p_i,
                           pvl.astype(jnp.float32))
        # merge partials across the seq ring (tiny collectives: [B,Kv,G,*])
        m_g = lax.pmax(m_i, "seq")
        corr = jnp.exp(m_i - m_g)
        l_g = lax.psum(l_i * corr, "seq")
        acc_g = lax.psum(acc_i * corr[..., None], "seq")

        # suffix block (replicated): masked scores + merge with prefix
        s_s = jnp.einsum("btkgh,bskh->bktgs", qg,
                         ck.astype(compute_dtype),
                         preferred_element_type=jnp.float32) * scale
        s_s = jnp.where(suf_mask[:, None, None, None, :], s_s, NEG)
        m_s = jnp.max(s_s, axis=-1)
        p_s = jnp.exp(s_s - m_s[..., None])
        p_s = jnp.where(s_s <= NEG, 0.0, p_s)
        l_s = jnp.sum(p_s, axis=-1)
        acc_s = jnp.einsum("bktgs,bskh->bktgh", p_s,
                           cv.astype(jnp.float32))

        m_f = jnp.maximum(m_g, m_s)
        c_g, c_s = jnp.exp(m_g - m_f), jnp.exp(m_s - m_f)
        denom = l_g * c_g + l_s * c_s
        out = (acc_g * c_g[..., None] + acc_s * c_s[..., None]) \
            / jnp.maximum(denom, 1e-30)[..., None]
        out = out.transpose(0, 2, 1, 3, 4).reshape(B, 1, Kv * G, H)
        x = x + attn_output(out.astype(x.dtype), lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        return x, (ck, cv)

    x, (new_sk, new_sv) = lax.scan(layer, x, (layers, pk, pv, sck, scv))
    logits = final_logits(head, cfg, x)
    return logits[:, -1, :], new_sk, new_sv


def _sp_body(layers, head, tokens, *, cfg: ModelConfig, impl: str):
    """Per-device chunk of the model (inside shard_map, manual over seq)."""
    idx = lax.axis_index("seq")
    B, Tl = tokens.shape
    positions = idx * Tl + jnp.arange(Tl)[None, :] + jnp.zeros(
        (B, 1), jnp.int32)                                   # [B,Tl] global
    x, cos, sin = embed_tokens(head, cfg, tokens, positions)
    compute_dtype = jnp.dtype(cfg.dtype)

    def layer(x, lp):
        lp = jax.tree.map(lambda a: a.astype(compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
        if impl == "ring":
            out = ring_attention(q, k, v, positions, positions)
        else:
            out = ulysses_attention(q, k, v, positions)
        x = x + attn_output(out, lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        return x, (k.astype(compute_dtype), v.astype(compute_dtype))

    x, (ks, vs) = lax.scan(layer, x, layers)
    logits = final_logits(head, cfg, x)
    return logits, (ks, vs)
