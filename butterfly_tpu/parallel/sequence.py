"""Sequence/context parallelism: ring attention + Ulysses (long context).

The reference never mentions long-context mechanisms (SURVEY.md §5: absent
from all 6 files); this realizes the survey's required surface the TPU way:

* **Ring attention** (context parallel): Q/K/V are sequence-sharded over
  the `seq` mesh axis. Each of the N ring steps computes the visiting
  K/V block's *partial flash statistics* — the Pallas online-softmax
  kernel on TPU, its jnp twin elsewhere (`ops/ring_attention`, ISSUE 20;
  the jnp leg is the jax-0.4.37/CPU fallback) — folds them into the
  running stats with the associative merge, then rotates K/V (+ their
  positions, + int8 scales) to the next neighbor with `lax.ppermute`.
  On TPU the ring rides neighbor ICI links and the permute overlaps the
  block's kernel. Causality comes from comparing rotated K positions to
  local Q positions, so any chunk order works and no step is skipped
  (static schedule).

* **Ulysses**: `lax.all_to_all` reshards [B, T/N, H_all] -> [B, T, H/N]
  (heads scatter, sequence gathers), runs ordinary full attention on the
  now-complete local sequence for its head group, and reshards back.
  Requires num_kv_heads % N == 0; ring has no such constraint.

* **sp_forward**: whole-model long-context prefill under shard_map manual
  over {'seq'} — norms/MLP/MoE are token-pointwise (trivially sequence-
  parallel), attention uses ring or Ulysses; `tensor`/`data` axes remain
  GSPMD-auto inside, so SP composes with TP. Returns logits and the
  sequence-sharded KV cache (each device keeps the K/V it computed —
  that sharded layout IS the context-parallel cache). Under
  `kv_quant="int8"` each device quantizes its chunk ONCE and every
  attention read goes through codes+scales (dequant-in-kernel, the pool
  representation) — the sharded cache comes back quantized, so a 128k
  prefix costs a quarter of the bf16 HBM.

Masking uses the sanitized-position contract of `ops/ring_attention`:
the ONE predicate everywhere is `k_pos <= q_pos`; invalid key slots
(prompt padding past the real length, unwritten suffix slots) carry
position `INVALID_POS`, so causality, raggedness and padding are a
single comparison with no per-case mask tensors.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from butterfly_tpu.core import compat
from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import (
    KVCache, Params, _cast_float, attend, attn_output, embed_tokens,
    ffn_block, final_logits, pre_norm, qkv_proj, quantize_kv,
    update_cache_layer, update_cache_layer_q)
from butterfly_tpu.ops.ring_attention import (
    INVALID_POS, block_stats, finalize_stats, merge_stats, zero_stats)


def ring_stats(q: jax.Array, k: jax.Array, v: jax.Array,
               q_pos: jax.Array, k_pos: jax.Array,
               axis_name: str = "seq",
               k_scale: Optional[jax.Array] = None,
               v_scale: Optional[jax.Array] = None,
               kernel: Optional[bool] = None):
    """Merged (unfinalized) flash stats over all N ring blocks.

    The ring loop of `ring_attention` without the final normalization:
    callers that must fold in ANOTHER key segment (the paged-pool
    prefix of a chunked seq-parallel prefill) merge these stats with
    that segment's before one shared `finalize_stats`.
    """
    B, Tq, Nq, H = q.shape
    N = compat.axis_size(axis_name)
    perm = [(i, (i + 1) % N) for i in range(N)]
    stats = zero_stats(B, Nq, Tq, H)

    def step(carry, _):
        stats, k, v, k_pos, ks, vs = carry
        blk = block_stats(q, k, v, q_pos, k_pos, ks, vs, kernel=kernel)
        stats = merge_stats(stats, blk)
        k, v, k_pos, ks, vs = lax.ppermute(
            (k, v, k_pos, ks, vs), axis_name, perm)
        return (stats, k, v, k_pos, ks, vs), None

    (stats, _, _, _, _, _), _ = lax.scan(
        step, (stats, k, v, k_pos, k_scale, v_scale), None, length=N)
    return stats


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   q_pos: jax.Array, k_pos: jax.Array,
                   axis_name: str = "seq",
                   k_scale: Optional[jax.Array] = None,
                   v_scale: Optional[jax.Array] = None,
                   kernel: Optional[bool] = None) -> jax.Array:
    """Causal GQA over a sequence ring (call inside shard_map).

    q: [B, Tq, Nq, H] local chunk; float k/v: [B, Tk, Kv, H] local
    chunk; int8 k/v: codes [B, Kv, Tk, H] with k_scale/v_scale
    [B, Kv, Tk] (the pool representation — dequantized inside the
    block kernel). q_pos/k_pos: [B, T*] absolute positions, invalid
    keys sanitized to INVALID_POS. Returns [B, Tq, Nq, H].
    """
    return finalize_stats(
        ring_stats(q, k, v, q_pos, k_pos, axis_name, k_scale, v_scale,
                   kernel=kernel), q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      q_pos: jax.Array, axis_name: str = "seq") -> jax.Array:
    """All-to-all head<->sequence reshard + local full causal attention.

    q: [B, T/N, Nq, H]; k/v: [B, T/N, Kv, H]. Needs Nq % N == 0; when
    Kv < N (realistic GQA, e.g. Llama-3 Kv=8 on a 16-way seq axis) and
    N % Kv == 0, KV heads are REPLICATED r = N/Kv times before the
    all_to_all so device d receives the kv head (d // r) its q-head
    block contracts with — the seq axis is no longer capped at Kv, at
    the cost of r x the K/V all_to_all volume. Returns [B, T/N, Nq, H].
    """
    N = compat.axis_size(axis_name)
    B, Tl, Nq, H = q.shape
    Kv = k.shape[2]
    if Kv % N != 0:
        if N % Kv != 0 or Nq % N != 0:
            raise ValueError(
                f"ulysses needs Kv % N == 0 or (N % Kv == 0 and "
                f"Nq % N == 0); got Nq={Nq}, Kv={Kv}, N={N}")
        # head replication: q heads [d*Nq/N, (d+1)*Nq/N) all map to kv
        # head d // r (block size Nq/N divides the GQA group G = Nq/Kv
        # because Kv < N), so repeating each kv head r times puts the
        # right copy on every device after the head-scatter.
        r = N // Kv
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)
    # heads scatter (axis 2), sequence gathers (axis 1)
    qq = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kk = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vv = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    # full global positions for the gathered sequence
    pos = lax.all_gather(q_pos, axis_name, axis=1, tiled=True)  # [B, T]
    mask = pos[:, None, :] <= pos[:, :, None]                   # [B,T,T]
    out = attend(qq, kk, vv, mask, None)  # attend() reads only shapes+mask
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


# ---------------------------------------------------------------------------
# Whole-model sequence-parallel prefill
# ---------------------------------------------------------------------------

def sp_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
               mesh: Mesh, impl: str = "ring", kv_quant: str = "none"
               ) -> Tuple[jax.Array, KVCache]:
    """Long-context prefill with activations sharded over `seq`.

    tokens: [B, T] (T divisible by the seq axis). Returns
    (logits [B,T,V] seq-sharded on T, KVCache with S = T seq-sharded —
    int8 codes+scales when kv_quant="int8", sharded over the S dim of
    the kv-major layout).
    """
    N = mesh.shape["seq"]
    B, T = tokens.shape
    if T % N != 0:
        raise ValueError(f"seq len {T} not divisible by seq axis {N}")
    if kv_quant not in ("none", "int8"):
        raise ValueError(f"unknown kv quant {kv_quant!r}")
    quant = kv_quant == "int8"

    body = partial(_sp_body, cfg=cfg, impl=impl, quant=quant)
    layer_in = jax.tree.map(lambda _: P(), params["layers"])
    head_in = jax.tree.map(lambda _: P(), {
        k: v for k, v in params.items() if k != "layers"})
    if quant:
        cache_out = (P(None, None, None, "seq", None),   # codes [L,B,Kv,T,H]
                     P(None, None, None, "seq", None),
                     P(None, None, None, "seq"),         # scales [L,B,Kv,T]
                     P(None, None, None, "seq"))
    else:
        cache_out = (P(None, None, "seq"),               # [L,B,T,Kv,H]
                     P(None, None, "seq"))
    fn = compat.shard_map(
        body, mesh,
        in_specs=(layer_in, head_in, P(None, "seq")),
        out_specs=(P(None, "seq"), cache_out),
        axis_names={"seq"})
    logits, cache_parts = fn(params["layers"],
                             {k: v for k, v in params.items()
                              if k != "layers"},
                             tokens)
    length = jnp.full((B,), T, jnp.int32)
    if quant:
        ks, vs, ksc, vsc = cache_parts
        cache = KVCache(k=ks, v=vs, length=length, k_scale=ksc, v_scale=vsc)
    else:
        ks, vs = cache_parts
        cache = KVCache(k=ks, v=vs, length=length)
    return logits, cache


def sp_decode_step(params: Params, cfg: ModelConfig, tokens: jax.Array,
                   positions: jax.Array, prefix: KVCache, suffix: KVCache,
                   mesh: Mesh,
                   prefix_len: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, KVCache]:
    """One decode step consuming sp_forward's sequence-sharded cache.

    The long prefix stays sharded over `seq` exactly where prefill left it
    (never regathered); generated tokens live in a small replicated
    contiguous `suffix` cache. Attention is computed as one online-softmax
    merge (the `ops/ring_attention` stats algebra): each device attends its
    local prefix chunk into partial (m, l, acc), the partials merge across
    the ring with pmax/psum — collectives sized [B,Nq,H], never [B,T,*] —
    and the suffix block folds in locally via the same `merge_stats`.

    int8: when `prefix.quantized`, the suffix cache must be quantized too
    (init_cache(..., quant="int8")) — both segments then read codes +
    scales exactly like the dense int8 reference reads its cache back.

    tokens/positions: [B,1] (positions = prefix length + step).
    Returns (last-token logits [B,V], suffix cache with the new K/V).

    prefix_len [B]: number of REAL prefix tokens per row; prefix slots at
    or past it are masked out. Defaults to prefix.length (no padding).
    generate_long pads prompts up to a multiple of the seq axis, so the
    tail of the sharded prefix holds pad K/V that must not be attended.

    Capacity contract (as for the paged pool, where the host allocator
    guarantees pages): the caller must size the suffix cache for the
    whole decode run — a step past suffix.max_seq would clamp its write
    onto the last slot. Checked eagerly when lengths are concrete.
    """
    if not isinstance(suffix.length, jax.core.Tracer):
        if int(jnp.max(suffix.length)) >= suffix.max_seq:
            raise ValueError(
                f"suffix cache full ({suffix.max_seq} slots): size "
                "init_cache(max_seq=...) for the whole decode run")
    if prefix_len is None:
        prefix_len = prefix.length
    quant = prefix.quantized
    if quant != suffix.quantized:
        raise ValueError("prefix and suffix caches must agree on kv_quant")
    body = partial(_sp_decode_body, cfg=cfg, quant=quant)
    layer_in = jax.tree.map(lambda _: P(), params["layers"])
    head = {k: v for k, v in params.items() if k != "layers"}
    head_in = jax.tree.map(lambda _: P(), head)
    if quant:
        seq_kv = P(None, None, None, "seq", None)  # codes [L,B,Kv,T,H]
        seq_sc = P(None, None, None, "seq")        # scales [L,B,Kv,T]
        cache_args = (prefix.k, prefix.v, prefix.k_scale, prefix.v_scale,
                      suffix.k, suffix.v, suffix.k_scale, suffix.v_scale)
        cache_in = (seq_kv, seq_kv, seq_sc, seq_sc, P(), P(), P(), P())
        out_specs = (P(), P(), P(), P(), P())
    else:
        seq_kv = P(None, None, "seq")   # [L,B,T,Kv,H]: local T chunk
        cache_args = (prefix.k, prefix.v, suffix.k, suffix.v)
        cache_in = (seq_kv, seq_kv, P(), P())
        out_specs = (P(), P(), P())
    fn = compat.shard_map(
        body, mesh,
        in_specs=(layer_in, head_in, P(), P()) + cache_in + (P(), P()),
        out_specs=out_specs,
        axis_names={"seq"})
    out = fn(params["layers"], head, tokens, positions, *cache_args,
             suffix.length, prefix_len)
    if quant:
        logits, sk, sv, sks, svs = out
        new_suffix = KVCache(sk, sv, suffix.length + 1,
                             k_scale=sks, v_scale=svs)
    else:
        logits, sk, sv = out
        new_suffix = KVCache(sk, sv, suffix.length + 1)
    return logits, new_suffix


def _sp_decode_body(layers, head, tokens, positions, *rest,
                    cfg: ModelConfig, quant: bool):
    """Per-device decode step (inside shard_map, manual over seq)."""
    if quant:
        pk, pv, pks, pvs, sck, scv, scks, scvs, slen, plen = rest
    else:
        pk, pv, sck, scv, slen, plen = rest
        pks = pvs = scks = scvs = None

    B = tokens.shape[0]
    Smax = sck.shape[3] if quant else sck.shape[2]
    Tl = pk.shape[3] if quant else pk.shape[2]
    x, cos, sin = embed_tokens(head, cfg, tokens, positions)
    compute_dtype = jnp.dtype(cfg.dtype)
    # sanitized key positions, built ONCE outside the layer scan:
    # suffix slot j holds the token written at global position plen + j;
    # slots past slen (this step's write is slot slen itself, visible)
    # and prefix pad slots (generate_long's divisibility padding) are
    # INVALID_POS, so the kernels' single k_pos <= q_pos comparison is
    # the whole mask.
    j = jnp.arange(Smax)
    suf_pos = jnp.where(j[None, :] <= slen[:, None],
                        plen[:, None] + j[None, :], INVALID_POS)  # [B,Smax]
    idx = lax.axis_index("seq")
    gpos = idx * Tl + jnp.arange(Tl)                              # [Tl]
    pre_pos = jnp.where(gpos[None, :] < plen[:, None],
                        gpos[None, :], INVALID_POS)               # [B,Tl]

    def layer(x, scanned):
        if quant:
            lp, pkl, pvl, pksl, pvsl, ck, cv, cks, cvs = scanned
        else:
            lp, pkl, pvl, ck, cv = scanned
            pksl = pvsl = cks = cvs = None
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)     # q [B,1,Nq,H]
        if quant:
            ck, cv, cks, cvs = update_cache_layer_q(ck, cv, cks, cvs,
                                                    k, v, slen)
        else:
            ck, cv = update_cache_layer(ck, cv, k, v, slen)

        # local prefix chunk -> partial flash stats (Pallas kernel on
        # TPU, jnp twin elsewhere), merged across the seq ring with
        # tiny collectives: [B,Nq,*], never [B,T,*]
        m_i, l_i, acc_i = block_stats(q, pkl, pvl, positions, pre_pos,
                                      pksl, pvsl)
        m_g = lax.pmax(m_i, "seq")
        corr = jnp.exp(m_i - m_g)
        l_g = lax.psum(l_i * corr, "seq")
        acc_g = lax.psum(acc_i * corr[..., None], "seq")

        # suffix block (replicated): same stats helper, local merge
        suf = block_stats(q, ck, cv, positions, suf_pos, cks, cvs)
        out = finalize_stats(merge_stats((m_g, l_g, acc_g), suf), x.dtype)
        x = x + attn_output(out, lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        if quant:
            return x, (ck, cv, cks, cvs)
        return x, (ck, cv)

    if quant:
        xs = (layers, pk, pv, pks, pvs, sck, scv, scks, scvs)
    else:
        xs = (layers, pk, pv, sck, scv)
    x, new_suffix = lax.scan(layer, x, xs)
    logits = final_logits(head, cfg, x)
    return (logits[:, -1, :],) + new_suffix


def sp_chunk_body(layers, head, tokens, start, *rest, cfg: ModelConfig,
                  quant: bool):
    """Per-device slice of ONE paged long-prompt prefill chunk (inside
    shard_map, manual over `seq`) — the serving-path sibling of
    `_sp_body` (ISSUE 20 move 3).

    tokens: local [B=1, Cl] slice of the (padded) chunk buffer whose
    first token sits at absolute position `start` (scalar — also the
    count of already-flushed pool-prefix tokens). `rest` is the slot's
    REPLICATED gathered pool prefix: (pk, pv) [L,B,S,Kv,H] when float,
    (pk, pv, pks, pvs) codes [L,B,Kv,S,H] + scales [L,B,Kv,S] when the
    pool is int8. Each query attends that prefix locally (replicated →
    plain block_stats, no collective) and the fresh chunk via the seq
    ring; the two partials share one finalize. Chunk padding needs no
    sanitization — pad positions exceed every real query's, so the
    kernels' k_pos <= q_pos drops them — and the pad K/V rows are
    routed to the null page by the caller's scatter. Returns
    (logits [B,Cl,V], per-layer fresh-chunk K/V in pool
    representation: int8 codes+scales when quant, compute-dtype floats
    otherwise).
    """
    if quant:
        pk, pv, pks, pvs = rest
    else:
        pk, pv = rest
        pks = pvs = None
    B, Cl = tokens.shape
    S = pk.shape[3] if quant else pk.shape[2]
    idx = lax.axis_index("seq")
    positions = start + idx * Cl + jnp.arange(Cl)[None, :] + jnp.zeros(
        (B, 1), jnp.int32)                                   # [B,Cl] global
    x, cos, sin = embed_tokens(head, cfg, tokens, positions)
    compute_dtype = jnp.dtype(cfg.dtype)
    # sanitized prefix key positions: exactly the flushed tokens
    # (< start) are attendable; null-page slots and the unwritten tail
    # go to INVALID_POS (built ONCE outside the layer scan)
    gpos = jnp.arange(S)[None, :]
    pre_pos = jnp.broadcast_to(
        jnp.where(gpos < start, gpos, INVALID_POS), (B, S))  # [B,S]

    def layer(x, scanned):
        if quant:
            lp, pkl, pvl, pksl, pvsl = scanned
        else:
            lp, pkl, pvl = scanned
            pksl = pvsl = None
        lp = jax.tree.map(lambda a: _cast_float(a, compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
        pre = block_stats(q, pkl, pvl, positions, pre_pos, pksl, pvsl)
        if quant:
            # quantize the local chunk ONCE (the pool representation);
            # fresh-chunk reads go through codes+scales like the dense
            # int8 reference reading its just-written pool back
            kq, ks = quantize_kv(jnp.moveaxis(k, 2, 1))      # [B,Kv,Cl,H]
            vq, vs = quantize_kv(jnp.moveaxis(v, 2, 1))
            fresh = ring_stats(q, kq, vq, positions, positions,
                               k_scale=ks, v_scale=vs)
            kv_out = (kq, vq, ks, vs)
        else:
            fresh = ring_stats(q, k, v, positions, positions)
            kv_out = (k.astype(compute_dtype), v.astype(compute_dtype))
        out = finalize_stats(merge_stats(pre, fresh), x.dtype)
        x = x + attn_output(out, lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        return x, kv_out

    xs = (layers, pk, pv, pks, pvs) if quant else (layers, pk, pv)
    x, kv = lax.scan(layer, x, xs)
    logits = final_logits(head, cfg, x)
    return logits, kv


def _sp_body(layers, head, tokens, *, cfg: ModelConfig, impl: str,
             quant: bool):
    """Per-device chunk of the model (inside shard_map, manual over seq)."""
    idx = lax.axis_index("seq")
    B, Tl = tokens.shape
    positions = idx * Tl + jnp.arange(Tl)[None, :] + jnp.zeros(
        (B, 1), jnp.int32)                                   # [B,Tl] global
    x, cos, sin = embed_tokens(head, cfg, tokens, positions)
    compute_dtype = jnp.dtype(cfg.dtype)

    def layer(x, lp):
        lp = jax.tree.map(lambda a: a.astype(compute_dtype), lp)
        h = pre_norm(x, lp["ln1"], cfg)
        q, k, v = qkv_proj(h, lp["attn"], cfg, cos, sin)
        if quant:
            # quantize the local chunk ONCE (the representation the
            # sharded cache keeps); every attention read then goes
            # through codes+scales, matching what the dense int8
            # reference reads back from its just-written cache.
            kq, ks = quantize_kv(jnp.moveaxis(k, 2, 1))      # [B,Kv,Tl,H]
            vq, vs = quantize_kv(jnp.moveaxis(v, 2, 1))
            if impl == "ring":
                out = ring_attention(q, kq, vq, positions, positions,
                                     k_scale=ks, v_scale=vs)
            else:
                # ulysses gathers full sequences for dense attend; feed
                # it the dequantized values (same operand set, no
                # scale-plumbing through the all_to_alls)
                kf = jnp.moveaxis(kq.astype(jnp.float32) * ks[..., None],
                                  1, 2).astype(compute_dtype)
                vf = jnp.moveaxis(vq.astype(jnp.float32) * vs[..., None],
                                  1, 2).astype(compute_dtype)
                out = ulysses_attention(q, kf, vf, positions)
            kv_out = (kq, vq, ks, vs)
        else:
            if impl == "ring":
                out = ring_attention(q, k, v, positions, positions)
            else:
                out = ulysses_attention(q, k, v, positions)
            kv_out = (k.astype(compute_dtype), v.astype(compute_dtype))
        x = x + attn_output(out, lp["attn"], cfg)
        x = x + ffn_block(pre_norm(x, lp["ln2"], cfg), lp, cfg)
        return x, kv_out

    x, kv = lax.scan(layer, x, layers)
    logits = final_logits(head, cfg, x)
    return logits, kv
