"""Expert parallelism: GShard-style capacity-based MoE dispatch/combine.

TPU-native realization of the reference's MoE expert-parallel requirement
(BASELINE.json configs[3], Mixtral-8x7B over ICI; the reference itself has
no implementation — SURVEY.md §0). Instead of NCCL all_to_all calls on
token buffers, the dispatch and combine are *einsums with one-hot dispatch
tensors*; with

  * tokens sharded over `data` (batch dim), and
  * experts sharded over `expert` (leading E dim of w_gate/w_up/w_down),

GSPMD lowers the dispatch einsum to the all-to-all that moves token
activations to their experts' devices and the combine einsum to the
reverse — the canonical TPU MoE lowering (GShard, Mesh-TF lineage).

Capacity: each expert processes at most C = ceil(cf * k * T / E) tokens
per sequence; overflow tokens are dropped (their FFN contribution is zero,
residual passes through — standard Switch/GShard semantics). With
cf >= E / k... cf large enough that C >= k*T, nothing drops and the result
equals the dense reference `models.common.moe_block` exactly — that is the
parity test. Inference-only: no load-balancing aux loss.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import ACTIVATIONS, Params


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """with_sharding_constraint iff a mesh with the spec's axes is active."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or not mesh.axis_names:
        return x
    names = set()
    for part in spec:
        if part is None:
            continue
        names.update(part if isinstance(part, tuple) else (part,))
    if not names.issubset(set(mesh.axis_names)):
        return x
    return lax.with_sharding_constraint(x, spec)


def expert_capacity(cfg: ModelConfig, tokens_per_seq: int) -> int:
    """Per-sequence per-expert token slots."""
    c = math.ceil(cfg.moe_capacity_factor * cfg.num_experts_per_tok
                  * tokens_per_seq / cfg.num_experts)
    return max(1, min(c, cfg.num_experts_per_tok * tokens_per_seq))


def moe_block_ep(x: jax.Array, p: Params, cfg: ModelConfig,
                 capacity: Optional[int] = None) -> jax.Array:
    """Expert-parallel MoE FFN: dispatch -> expert SwiGLU -> combine.

    x: [B,T,D]. Experts' weight leaves p["w_*"]: [E,D,F]/[E,F,D] (one
    layer's slice — the layer scan strips the L dim). Returns [B,T,D].
    """
    B, T, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    C = capacity or expert_capacity(cfg, T)

    router_logits = jnp.einsum("btd,de->bte", x,
                               p["router"]).astype(jnp.float32)
    gates, idx = lax.top_k(router_logits, k)          # [B,T,k]
    gates = jax.nn.softmax(gates, axis=-1)

    # Slot assignment: expert e takes tokens in (t, k)-priority order.
    emask = jax.nn.one_hot(idx, E, dtype=jnp.int32)    # [B,T,k,E]
    flat = emask.reshape(B, T * k, E)
    pos = jnp.cumsum(flat, axis=1) - 1                 # position in expert
    pos = pos.reshape(B, T, k, E)
    keep = (pos < C) & (emask > 0)                     # overflow -> drop
    emask = emask.astype(jnp.float32)

    # dispatch[b,t,e,c] = 1 iff token (b,t) occupies slot c of expert e
    slot = jax.nn.one_hot(pos, C, dtype=jnp.float32)   # [B,T,k,E,C]
    dispatch = jnp.einsum("btke,btkec->btec",
                          keep.astype(jnp.float32) * emask, slot)
    combine = jnp.einsum("btk,btke,btkec->btec",
                         gates, keep.astype(jnp.float32) * emask, slot)

    # The all-to-all: tokens (data-sharded) -> expert-major layout.
    xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(x.dtype), x)
    xin = _constrain(xin, P("expert", "data", None, None))

    act = ACTIVATIONS[cfg.act]
    g = jnp.einsum("ebcd,edf->ebcf", xin, p["w_gate"])
    u = jnp.einsum("ebcd,edf->ebcf", xin, p["w_up"])
    y = jnp.einsum("ebcf,efd->ebcd", act(g) * u, p["w_down"])
    y = _constrain(y, P("expert", "data", None, None))

    # Reverse all-to-all + weighted combine back to token-major layout.
    out = jnp.einsum("btec,ebcd->btd", combine.astype(y.dtype), y)
    return _constrain(out, P("data", None, None))
