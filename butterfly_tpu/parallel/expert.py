"""Expert parallelism: GShard-style capacity-based MoE dispatch/combine.

TPU-native realization of the reference's MoE expert-parallel requirement
(BASELINE.json configs[3], Mixtral-8x7B over ICI; the reference itself has
no implementation — SURVEY.md §0). Two dispatch mechanisms:

* **Scatter + explicit `lax.all_to_all`** (the scalable path, prefill):
  tokens are sequence-sharded over the `expert` axis inside a shard_map;
  each device counting-sorts its local routing assignments into a
  per-destination send buffer [N, ne, C, D] (scatter by computed slot),
  one tiled all_to_all moves tokens to their experts' devices, the local
  experts run their SwiGLU, and the reverse all_to_all returns outputs
  for a gather+weighted combine. Memory is O(B·T·k) indices + the [E,C,D]
  buffers — never a [B,T,k,E,C] one-hot.

* **One-hot einsum dispatch** (fallback: decode steps and shapes the
  seq split doesn't divide): dispatch/combine as einsums with one-hot
  tensors that GSPMD lowers itself (Mesh-TF lineage). Fine at T==1;
  at long prefill lengths the [B,T,k,E,C] dispatch tensor dwarfs the
  activations, hence the path above (VERDICT r2 weak item 5).

Capacity: each expert processes at most C tokens per sequence (einsum
path) or per source shard (a2a path); overflow tokens are dropped (their
FFN contribution is zero, residual passes through — standard
Switch/GShard semantics). With cf large enough that nothing drops the
result equals the dense reference `models.common.moe_block` exactly —
that is the parity test. Inference-only: no load-balancing aux loss.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import ACTIVATIONS, Params
from butterfly_tpu.quant.int8 import qeinsum


def _constrain(x: jax.Array, spec: P) -> jax.Array:
    """with_sharding_constraint iff a mesh with the spec's axes is active."""
    from butterfly_tpu.ops.flash_attention import _abstract_mesh
    mesh = _abstract_mesh()   # None on jax 0.4.x: no ambient mesh exists
    if mesh is None or not mesh.axis_names:
        return x
    names = set()
    for part in spec:
        if part is None:
            continue
        names.update(part if isinstance(part, tuple) else (part,))
    if not names.issubset(set(mesh.axis_names)):
        return x
    return lax.with_sharding_constraint(x, spec)


def expert_capacity(cfg: ModelConfig, tokens_per_seq: int) -> int:
    """Per-sequence per-expert token slots."""
    c = math.ceil(cfg.moe_capacity_factor * cfg.num_experts_per_tok
                  * tokens_per_seq / cfg.num_experts)
    return max(1, min(c, cfg.num_experts_per_tok * tokens_per_seq))


def moe_block_ep(x: jax.Array, p: Params, cfg: ModelConfig,
                 capacity: Optional[int] = None) -> jax.Array:
    """Expert-parallel MoE FFN: dispatch -> expert SwiGLU -> combine.

    x: [B,T,D]. Experts' weight leaves p["w_*"]: [E,D,F]/[E,F,D] (one
    layer's slice — the layer scan strips the L dim). Returns [B,T,D].

    Routes through the scatter+all_to_all dispatch when a live mesh has
    an active `expert` axis that divides T (prefill); decode steps and
    non-dividing shapes fall back to the one-hot einsum dispatch.

    `capacity` is per-sequence-per-expert slots on both paths (the a2a
    path converts it to its pooled per-shard buffer size so the no-drop
    contract is path-independent). Under a DROPPING capacity the paths
    may drop different tokens: the einsum path budgets per sequence, the
    a2a path pools its shard's budget — same volume, different victims.
    """
    from butterfly_tpu.ops.flash_attention import _abstract_mesh, _auto_axes
    mesh = _abstract_mesh()   # None on jax 0.4.x -> einsum fallback
    if (mesh is not None and not mesh.empty
            and "expert" in _auto_axes(mesh)   # not Manual from an outer map
            and mesh.shape["expert"] > 1
            and x.shape[1] > 1                 # decode: einsum path is fine
            and x.shape[1] % mesh.shape["expert"] == 0
            and cfg.num_experts % mesh.shape["expert"] == 0):
        return _moe_ep_a2a(x, p, cfg, capacity)
    return _moe_ep_einsum(x, p, cfg, capacity)


def _moe_ep_einsum(x: jax.Array, p: Params, cfg: ModelConfig,
                   capacity: Optional[int] = None) -> jax.Array:
    """One-hot einsum dispatch (GSPMD lowers the resharding itself)."""
    B, T, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    C = capacity or expert_capacity(cfg, T)

    from butterfly_tpu.models.common import route_tokens
    gates, idx = route_tokens(x, p["router"], k)      # [B,T,k]

    # Slot assignment: expert e takes tokens in (t, k)-priority order.
    emask = jax.nn.one_hot(idx, E, dtype=jnp.int32)    # [B,T,k,E]
    flat = emask.reshape(B, T * k, E)
    pos = jnp.cumsum(flat, axis=1) - 1                 # position in expert
    pos = pos.reshape(B, T, k, E)
    keep = (pos < C) & (emask > 0)                     # overflow -> drop
    emask = emask.astype(jnp.float32)

    # dispatch[b,t,e,c] = 1 iff token (b,t) occupies slot c of expert e
    slot = jax.nn.one_hot(pos, C, dtype=jnp.float32)   # [B,T,k,E,C]
    dispatch = jnp.einsum("btke,btkec->btec",
                          keep.astype(jnp.float32) * emask, slot)
    combine = jnp.einsum("btk,btke,btkec->btec",
                         gates, keep.astype(jnp.float32) * emask, slot)

    # The all-to-all: tokens (data-sharded) -> expert-major layout.
    xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(x.dtype), x)
    xin = _constrain(xin, P("expert", "data", None, None))

    act = ACTIVATIONS[cfg.act]
    g = qeinsum("ebcd,edf->ebcf", xin, p["w_gate"])
    u = qeinsum("ebcd,edf->ebcf", xin, p["w_up"])
    y = qeinsum("ebcf,efd->ebcd", act(g) * u, p["w_down"])
    y = _constrain(y, P("expert", "data", None, None))

    # Reverse all-to-all + weighted combine back to token-major layout.
    out = jnp.einsum("btec,ebcd->btd", combine.astype(y.dtype), y)
    return _constrain(out, P("data", None, None))


def _moe_ep_a2a(x: jax.Array, p: Params, cfg: ModelConfig,
                capacity: Optional[int] = None) -> jax.Array:
    """Scatter + explicit all_to_all dispatch (shard_map over `expert`).

    Tokens are sequence-sharded over the expert axis; each device
    counting-sorts its local (token, k) assignments into per-destination
    send slots and ONE tiled all_to_all moves activations to their
    experts' devices (reverse for outputs). Capacity C is per (source
    shard, expert) — with a no-drop cf this equals the einsum path and
    the dense reference exactly.
    """
    from butterfly_tpu.ops.flash_attention import _abstract_mesh
    mesh = _abstract_mesh()   # non-None: moe_block_ep gates on it
    N = mesh.shape["expert"]
    B, T, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    ne = E // N                          # experts owned per shard
    Tl = T // N                          # local sequence chunk
    if capacity is not None:
        # The caller's `capacity` means per-sequence-per-expert (the
        # einsum path's unit). Pooled per-shard equivalent that keeps the
        # no-drop contract exact: B sequences x min(capacity, k*Tl)
        # worst-case assignments each (a sequence's hot tokens may all
        # land in one shard's chunk).
        C = min(capacity * B, k * B * Tl)
    else:
        C = expert_capacity(cfg, B * Tl)

    body = partial(_a2a_body, cfg=cfg, N=N, ne=ne, C=C)
    from butterfly_tpu.core import compat
    fn = compat.shard_map(
        body, mesh,
        in_specs=(P(None, "expert", None),
                  {"router": P(), "w_gate": P("expert"), "w_up": P("expert"),
                   "w_down": P("expert")}),
        out_specs=P(None, "expert", None),
        axis_names={"expert"})
    return fn(x, {kk: p[kk] for kk in
                  ("router", "w_gate", "w_up", "w_down")})


def _a2a_body(x, p, *, cfg: ModelConfig, N: int, ne: int, C: int):
    """Per-device half of the a2a dispatch (inside shard_map)."""
    B, Tl, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    A = B * Tl * k                                      # local assignments

    from butterfly_tpu.models.common import route_tokens
    gates, idx = route_tokens(x, p["router"], k)        # [B,Tl,k]

    # counting sort by expert: slot of assignment a within its expert
    g_flat = idx.reshape(A)                             # global expert ids
    onehot = jax.nn.one_hot(g_flat, E, dtype=jnp.int32)  # [A,E] (small)
    pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(A), g_flat]  # [A]
    keep = pos < C

    # scatter tokens into the send buffer [N, ne, C, D]; dropped/overflow
    # assignments get an out-of-range index (scatter mode drops them)
    dest = jnp.where(keep, g_flat * C + pos, N * ne * C)
    x_rep = jnp.repeat(x.reshape(B * Tl, D), k, axis=0)  # [A,D] per-assign
    send = jnp.zeros((N * ne * C, D), x.dtype).at[dest].set(
        x_rep, mode="drop").reshape(N, ne, C, D)

    # one tiled all_to_all each way; FFN runs expert-major in between
    recv = lax.all_to_all(send, "expert", 0, 0, tiled=True)  # [N,ne,C,D]
    xin = recv.transpose(1, 0, 2, 3).reshape(ne, N * C, D)
    act = ACTIVATIONS[cfg.act]
    gg = qeinsum("ecd,edf->ecf", xin, p["w_gate"])
    uu = qeinsum("ecd,edf->ecf", xin, p["w_up"])
    y = qeinsum("ecf,efd->ecd", act(gg) * uu, p["w_down"])
    y = y.reshape(ne, N, C, D).transpose(1, 0, 2, 3)
    y_back = lax.all_to_all(y, "expert", 0, 0, tiled=True)   # [N,ne,C,D]

    # gather each assignment's expert output and combine with its gate
    y_flat = jnp.take(y_back.reshape(N * ne * C, D), jnp.minimum(
        dest, N * ne * C - 1), axis=0)
    y_flat = jnp.where(keep[:, None], y_flat, 0.0).astype(x.dtype)
    out = y_flat.reshape(B, Tl, k, D) * gates[..., None].astype(x.dtype)
    return jnp.sum(out, axis=2)
