"""Transformer partitioner: param/cache/activation PartitionSpecs.

Realizes the reference's planned "Model Partitioning" layer
(/root/reference/CLAUDE.md:21 — "Algorithms to intelligently divide
transformer layers/attention heads") the TPU way: instead of manually
slicing tensors and issuing NCCL calls, we attach `PartitionSpec`s to every
leaf of the param/cache pytrees and let GSPMD lower the einsums to sharded
matmuls with `all-reduce`/`all-gather` placed at the Megatron-canonical
points:

* attention: wq/wk/wv column-parallel (heads sharded over `tensor`), wo
  row-parallel -> one all-reduce per attention block;
* MLP: w_up/w_gate column-parallel, w_down row-parallel -> one all-reduce
  per MLP block;
* MoE experts sharded over `expert` (dispatch handled in parallel/expert.py);
* embedding vocab-sharded; lm_head column-parallel over vocab;
* KV cache: batch over `data`, kv-heads over `tensor`.

Sharding is *advisory for layout, mandatory for memory*: a spec only ever
shards a dim that divides evenly by the mesh axis (else that dim is
replicated), so any (cfg, mesh) combination is valid. Tests verify parity
TP=1 vs TP=8 and assert the expected collectives appear in the compiled
HLO (SURVEY.md §7 stage 2).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import KVCache

Specs = Dict[str, Any]


def _div(n: int, mesh: Mesh, axis: str) -> Optional[str]:
    """Return `axis` if dim of size n shards evenly over it, else None."""
    return axis if n % mesh.shape[axis] == 0 and mesh.shape[axis] > 1 else None


def _div_multi(n: int, mesh: Mesh, *axes: str):
    """Largest prefix-combination of active `axes` that divides n.

    Tries the full product first, then drops leading axes — e.g.
    ("stage", "tensor") falls back to tensor-only when n isn't divisible
    by stage*tensor. Returns an axis tuple / name / None (P dim entry)."""
    for i in range(len(axes)):
        active = [a for a in axes[i:] if mesh.shape[a] > 1]
        size = 1
        for a in active:
            size *= mesh.shape[a]
        if active and n % size == 0:
            return tuple(active) if len(active) > 1 else active[0]
    return None


def param_specs(cfg: ModelConfig, mesh: Mesh) -> Specs:
    """PartitionSpec pytree mirroring models.common.init_params exactly.

    Layer-stacked leaves have a leading L dim; when pipeline parallelism is
    active (mesh axis `stage` > 1) that dim is sharded over `stage` so each
    stage group holds only its own layers' weights.
    """
    D, Nq, Kv, F, V = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                       cfg.intermediate_size, cfg.vocab_size)
    tp = lambda n: _div(n, mesh, "tensor")  # noqa: E731
    L = _div(cfg.num_layers, mesh, "stage")

    layers: Specs = {
        "ln1": {"scale": P(L, None)},
        "ln2": {"scale": P(L, None)},
        "attn": {
            "wq": P(L, None, tp(Nq), None),   # column-parallel (heads)
            "wk": P(L, None, tp(Kv), None),
            "wv": P(L, None, tp(Kv), None),
            "wo": P(L, tp(Nq), None, None),   # row-parallel -> all-reduce
        },
    }
    if cfg.use_bias:
        layers["ln1"]["bias"] = P(L, None)
        layers["ln2"]["bias"] = P(L, None)
        layers["attn"].update(
            bq=P(L, tp(Nq), None), bk=P(L, tp(Kv), None),
            bv=P(L, tp(Kv), None), bo=P(L, None),
        )
    if cfg.is_moe:
        E = cfg.num_experts
        ep = _div(E, mesh, "expert")
        layers["moe"] = {
            "router": P(L, None, None),
            "w_gate": P(L, ep, None, tp(F)),
            "w_up": P(L, ep, None, tp(F)),
            "w_down": P(L, ep, tp(F), None),
        }
    elif cfg.arch == "gpt2":
        layers["mlp"] = {
            "w_up": P(L, None, tp(F)), "b_up": P(L, tp(F)),
            "w_down": P(L, tp(F), None), "b_down": P(L, None),
        }
    else:
        layers["mlp"] = {
            "w_gate": P(L, None, tp(F)),
            "w_up": P(L, None, tp(F)),
            "w_down": P(L, tp(F), None),
        }

    specs: Specs = {
        "embed": {"tok": P(tp(V), None)},
        "layers": layers,
        "final_norm": {"scale": P(None)},
    }
    if cfg.pos_embedding == "learned":
        specs["embed"]["pos"] = P(None, None)
    if cfg.arch == "gpt2":
        specs["final_norm"]["bias"] = P(None)
    if not cfg.tie_embeddings:
        # Vocab over stage AND tensor: a pipeline mesh would otherwise
        # replicate the D*V head on every stage (VERDICT r2 weak item 4).
        # The matmul contracts the replicated D dim, so sharding only
        # splits the output — no extra all-reduce; logits are produced
        # vocab-sharded and consumers gather the (tiny) last-token slice.
        specs["lm_head"] = P(None, _div_multi(V, mesh, "stage", "tensor"))
    return specs


def cache_specs(cfg: ModelConfig, mesh: Mesh, quant: bool = False) -> KVCache:
    """Specs for the KVCache pytree: layers x stage (mirrors the param
    layout so each pipeline stage holds only its own layers' cache),
    batch x data, kv-heads x tensor. Float caches are [L,B,S,Kv,H];
    int8 caches are [L,B,Kv,S,H] + scale leaves [L,B,Kv,S] (see
    models.common.KVCache for why the dim orders differ)."""
    lspec = _div(cfg.num_layers, mesh, "stage")
    dspec = _div_any(mesh, "data")
    tspec = _div(cfg.num_kv_heads, mesh, "tensor")
    if quant:
        kv = P(lspec, dspec, tspec, None, None)
        sc = P(lspec, dspec, tspec, None)
    else:
        kv = P(lspec, dspec, None, tspec, None)
        sc = None
    return KVCache(k=kv, v=kv, length=P(dspec), k_scale=sc, v_scale=sc)


def _div_any(mesh: Mesh, axis: str) -> Optional[str]:
    """Axis name if it is active (>1); batch dims are chosen divisible."""
    return axis if mesh.shape[axis] > 1 else None


def paged_cache_specs(cfg: ModelConfig, mesh: Mesh, num_slots: int,
                      quant: bool = False):
    """Specs for the PagedKVCache pytree (serving under a mesh).

    Pool k/v_pages [L,P,Kv,page,H]: layers over `stage` (each pipeline
    stage owns only its local layers' pages, mirroring param_specs),
    kv-heads over `tensor` (matching the Megatron column-parallel wk/wv
    so paged writes stay local to the TP shard). The page-id dim P stays
    replicated: page ownership is a host-allocator concept and any slot
    may reference any page, so sharding P would turn every gather into a
    cross-`data` collective. Slot-indexed leaves (page_table [S,maxp],
    lengths [S]) shard slots over `data` when divisible — the decode step
    then runs data-parallel over slots. int8 pools add scale leaves
    [L,P,Kv*page] whose flat dim shards over `tensor` iff Kv does (a
    tensor chunk of the kv-major flat dim is exactly one kv-group's
    scales — see cache/paged.py layout notes).
    """
    from butterfly_tpu.cache.paged import PagedKVCache
    dslots = _div(num_slots, mesh, "data")
    lspec = _div(cfg.num_layers, mesh, "stage")
    tspec = _div(cfg.num_kv_heads, mesh, "tensor")
    kv = P(lspec, None, tspec, None, None)
    sc = P(lspec, None, tspec) if quant else None
    return PagedKVCache(k_pages=kv, v_pages=kv,
                        page_table=P(dslots, None), lengths=P(dslots),
                        k_scale_pages=sc, v_scale_pages=sc)


def shard_paged_cache(cache, cfg: ModelConfig, mesh: Mesh):
    specs = paged_cache_specs(cfg, mesh, cache.num_slots,
                              quant=cache.quantized)
    return jax.device_put(cache, to_shardings(specs, mesh))


def kv_window_specs(cfg: ModelConfig, mesh: Mesh, num_slots: int,
                    quant: bool = False):
    """Specs for the write-combined KV window (cache/paged.py KVWindow,
    [L, S, Kv, W, H]): slots over `data` with the block table / q rows,
    kv-heads over `tensor` with the pools — so staging, the kernel's
    window segment, and the flush scatter all stay local to the shard
    that owns the matching pool bytes. L stays replicated (the window
    only exists on the non-pipeline serving path; stage > 1 falls back
    to per-token writes)."""
    from butterfly_tpu.cache.paged import KVWindow
    dslots = _div(num_slots, mesh, "data")
    tspec = _div(cfg.num_kv_heads, mesh, "tensor")
    kv = P(None, dslots, tspec, None, None)
    sc = P(None, dslots, tspec, None) if quant else None
    return KVWindow(k=kv, v=kv, k_scale=sc, v_scale=sc)


def shard_kv_window(window, cfg: ModelConfig, mesh: Mesh):
    specs = kv_window_specs(cfg, mesh, window.k.shape[1],
                            quant=window.quantized)
    return jax.device_put(window, to_shardings(specs, mesh))


def warm_prefix_specs(d: Optional[str], t: Optional[str],
                      quant: bool) -> Tuple:
    """In_specs for the warm-prefix flash kernel's cached-context
    operands (ops/flash_attention.py warm-prefix prefill, ISSUE 13), in
    call order: (prefix_k, prefix_v, prefix_len[, k_scale, v_scale]).

    The prefix is the cache in the representation attend() consumes —
    float view [B, S, Kv, H], or int8 codes [B, Kv, S, H] + per-vector
    scales [B, Kv, S] — so batch/slots shard over `data` with the q
    rows and kv heads over `tensor` with the pools, exactly the axes
    paged_cache_specs/cache_specs give the backing cache. `d`/`t` are
    the axis names shardable_axes resolved for this call site (None =
    replicated), not a mesh: the kernel wrapper picks them per dispatch.
    """
    if quant:
        code = P(d, t, None, None)
        return (code, code, P(d), P(d, t, None), P(d, t, None))
    view = P(d, None, t, None)
    return (view, view, P(d))


def activation_spec(mesh: Mesh, seq_sharded: bool = False) -> P:
    """[B,T,D] activations: batch over data, optionally seq over `seq`."""
    return P(_div_any(mesh, "data"), "seq" if seq_sharded and
             mesh.shape["seq"] > 1 else None, None)


def logits_spec(cfg: ModelConfig, mesh: Mesh) -> P:
    return P(_div_any(mesh, "data"), None, _div(cfg.vocab_size, mesh, "tensor"))


# ---------------------------------------------------------------------------
# Application helpers
# ---------------------------------------------------------------------------

def to_shardings(specs, mesh: Mesh):
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, cfg: ModelConfig, mesh: Mesh):
    """device_put every param leaf to its partitioned layout."""
    return jax.device_put(params, to_shardings(param_specs(cfg, mesh), mesh))


def shard_cache(cache: KVCache, cfg: ModelConfig, mesh: Mesh) -> KVCache:
    return jax.device_put(cache, to_shardings(
        cache_specs(cfg, mesh, quant=cache.quantized), mesh))


# ---------------------------------------------------------------------------
# HLO inspection (test/debug aid: verify collective placement, SURVEY.md §7)
# ---------------------------------------------------------------------------

def compiled_hlo(fn, *args, mesh: Optional[Mesh] = None, **jit_kw) -> str:
    """Lower+compile fn under `mesh` and return optimized HLO text."""
    jfn = jax.jit(fn, **jit_kw)
    if mesh is not None:
        # compat.mesh_ctx resolves to set_mesh where it exists: that
        # also installs the abstract mesh that mesh-aware call sites
        # (kernel wrappers, EP a2a dispatch) consult during tracing —
        # matching how the engines actually run.
        from butterfly_tpu.core import compat
        with compat.mesh_ctx(mesh):
            lowered = jfn.lower(*args)
    else:
        lowered = jfn.lower(*args)
    return lowered.compile().as_text()


def count_collectives(hlo: str) -> Dict[str, int]:
    """Count collective ops in optimized HLO text, keyed by op name."""
    ops = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
           "collective-permute")
    counts = {op: 0 for op in ops}
    for line in hlo.splitlines():
        s = line.lstrip()
        # count op *instances*: lines like `%all-reduce.3 = ...` or
        # `ROOT %all-gather ...`, not parameter references. Async pairs
        # (`-start`/`-done`) are one logical collective: skip `-done`.
        if "=" not in s:
            continue
        lhs = s.split("=", 1)[0]
        if "-done" in lhs:
            continue
        for op in ops:
            if op in lhs:
                counts[op] += 1
    return counts
