"""Pipeline parallelism: GPipe microbatch schedule over the `stage` mesh axis.

TPU-native realization of the reference's planned pipeline-stage send/recv
(/root/reference/CLAUDE.md:19-22 names the layers; no implementation exists
— SURVEY.md §0). Instead of point-to-point NCCL send/recv between stage
processes, the whole pipeline is ONE SPMD program:

* layer-stacked params/cache keep their leading L dim; `shard_map` manual
  over `stage` gives each stage its local [L/S, ...] slice;
* stage handoff is `lax.ppermute` (XLA collective-permute — on TPU this
  rides neighbor ICI links, the canonical pipeline transport);
* the microbatch schedule is a `lax.scan` over M + S - 1 ticks (GPipe):
  tick t has stage s working on microbatch m = t - s; invalid (bubble)
  ticks compute on garbage and are masked out of all writes;
* `tensor`/`data` axes stay under GSPMD auto partitioning *inside* the
  body (shard_map axis_names={'stage'}), so TPxPP composes without manual
  collectives: the per-stage einsums still get their Megatron all-reduces
  from the partitioner's specs.

Bubble fraction is (S-1)/(M+S-1); pick num_microbatches >= 4*S for decode
throughput parity with the north star (BASELINE.json configs[2]).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import (
    KVCache, Params, embed_tokens, final_logits, make_mask, scan_layers)


def pipeline_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     cache: KVCache, mesh: Mesh,
                     num_microbatches: Optional[int] = None,
                     positions: Optional[jax.Array] = None,
                     fresh: bool = False
                     ) -> Tuple[jax.Array, KVCache]:
    """Full forward with the layer stack pipelined over `stage`.

    Embedding and LM head run under plain GSPMD (they are outside the
    stage loop; on a real pod they live with stage 0 / stage S-1 layer
    weights — replicated here, cheap relative to the stack). Requires
    cfg.num_layers % S == 0 and batch % num_microbatches == 0.
    """
    S = mesh.shape["stage"]
    B, T = tokens.shape
    if positions is None:
        positions = cache.length[:, None] + jnp.arange(T)[None, :]
    if S == 1:
        from butterfly_tpu.models.common import forward
        return forward(params, cfg, tokens, cache, positions, fresh=fresh)

    M = num_microbatches or _default_microbatches(B, S)
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if cfg.num_layers % S != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible by {S} stages")

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)

    body = partial(_pipeline_body, cfg=cfg, S=S, M=M, fresh=fresh)
    # Manual over `stage` only: layer-stacked leaves and the cache split
    # their leading L dim; activations/masks are replicated over stage.
    # tensor/data stay auto (GSPMD) inside.
    layer_in = jax.tree.map(lambda _: P("stage"), params["layers"])
    pipe = jax.shard_map(
        body, mesh=mesh,
        in_specs=(layer_in, P("stage"), P("stage"),
                  P(), P(), P(), P(), P()),
        out_specs=(P(), P("stage"), P("stage")),
        axis_names={"stage"}, check_vma=False)
    y, new_k, new_v = pipe(params["layers"], cache.k, cache.v,
                           x, positions, mask, cos, sin)

    logits = final_logits(params, cfg, y)
    return logits, KVCache(new_k, new_v, cache.length + T)


def _default_microbatches(B: int, S: int) -> int:
    """Largest divisor of B that is <= 2*S (keeps the bubble small without
    violating B % M == 0 for any batch size)."""
    best = 1
    for m in range(1, min(B, 2 * S) + 1):
        if B % m == 0:
            best = m
    return best


def _pipeline_body(layers, ck, cv, x, positions, mask, cos, sin,
                   *, cfg: ModelConfig, S: int, M: int,
                   fresh: bool = False):
    """Per-stage GPipe schedule (runs inside shard_map, manual over stage).

    layers/ck/cv are the local [L/S, ...] stage slice; x [B,T,D] etc. are
    full-batch and replicated over stage.
    """
    stage = lax.axis_index("stage")
    B = x.shape[0]
    mb = B // M

    # [M, mb, ...] microbatch views
    xs = x.reshape(M, mb, *x.shape[1:])
    pos_mb = positions.reshape(M, mb, *positions.shape[1:])
    mask_mb = mask.reshape(M, mb, *mask.shape[1:])
    cos_mb = cos.reshape(M, mb, *cos.shape[1:])
    sin_mb = sin.reshape(M, mb, *sin.shape[1:])

    state0 = jnp.zeros_like(xs[0])          # activation entering this stage
    out0 = jnp.zeros_like(xs)               # last stage's results
    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        state, ck, cv, outs = carry
        m = t - stage                        # microbatch this stage works on
        valid = (m >= 0) & (m < M)
        mc = jnp.clip(m, 0, M - 1)

        inp = jnp.where(stage == 0, xs[jnp.clip(t, 0, M - 1)], state)
        ck_m = lax.dynamic_slice_in_dim(ck, mc * mb, mb, axis=1)
        cv_m = lax.dynamic_slice_in_dim(cv, mc * mb, mb, axis=1)

        y, nk, nv = scan_layers(layers, cfg, inp, ck_m, cv_m,
                                pos_mb[mc], mask_mb[mc], cos_mb[mc],
                                sin_mb[mc], fresh)

        # write back cache/output only on valid (non-bubble) ticks
        nk = jnp.where(valid, nk, ck_m)
        nv = jnp.where(valid, nv, cv_m)
        ck = lax.dynamic_update_slice_in_dim(ck, nk, mc * mb, axis=1)
        cv = lax.dynamic_update_slice_in_dim(cv, nv, mc * mb, axis=1)

        rec = jnp.where(valid & (stage == S - 1), y, outs[mc])
        outs = lax.dynamic_update_index_in_dim(outs, rec, mc, axis=0)

        state = lax.ppermute(y, "stage", fwd_perm)
        return (state, ck, cv, outs), None

    (_, ck, cv, outs), _ = lax.scan(
        tick, (state0, ck, cv, out0), jnp.arange(M + S - 1))

    # outs is only meaningful on the last stage; replicate it via psum.
    outs = jnp.where(stage == S - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(outs, "stage")
    return outs.reshape(B, *x.shape[1:]), ck, cv
