"""Pipeline parallelism: GPipe microbatch schedule over the `stage` mesh axis.

TPU-native realization of the reference's planned pipeline-stage send/recv
(/root/reference/CLAUDE.md:19-22 names the layers; no implementation exists
— SURVEY.md §0). Instead of point-to-point NCCL send/recv between stage
processes, the whole pipeline is ONE SPMD program:

* layer-stacked params/cache keep their leading L dim; `shard_map` manual
  over `stage` gives each stage its local [L/S, ...] slice;
* stage handoff is `lax.ppermute` (XLA collective-permute — on TPU this
  rides neighbor ICI links, the canonical pipeline transport);
* the microbatch schedule is a `lax.scan` over M + S - 1 ticks (GPipe):
  tick t has stage s working on microbatch m = t - s; invalid (bubble)
  ticks compute on garbage and are masked out of all writes;
* `tensor`/`data` axes stay under GSPMD auto partitioning *inside* the
  body (shard_map axis_names={'stage'}), so TPxPP composes without manual
  collectives: the per-stage einsums still get their Megatron all-reduces
  from the partitioner's specs.

Bubble fraction is (S-1)/(M+S-1); pick num_microbatches >= 4*S for decode
throughput parity with the north star (BASELINE.json configs[2]).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from butterfly_tpu.core.config import ModelConfig
from butterfly_tpu.models.common import (
    KVCache, Params, embed_tokens, final_logits, make_mask, scan_layers)


def pipeline_forward(params: Params, cfg: ModelConfig, tokens: jax.Array,
                     cache: KVCache, mesh: Mesh,
                     num_microbatches: Optional[int] = None,
                     positions: Optional[jax.Array] = None,
                     fresh: bool = False,
                     virtual_stages: int = 1
                     ) -> Tuple[jax.Array, KVCache]:
    """Full forward with the layer stack pipelined over `stage`.

    Embedding and LM head run under plain GSPMD (they are outside the
    stage loop; on a real pod they live with stage 0 / stage S-1 layer
    weights — replicated here, cheap relative to the stack). Requires
    cfg.num_layers % S == 0 and batch % num_microbatches == 0.

    virtual_stages V > 1 selects the INTERLEAVED schedule (SURVEY.md §7
    stage 3 "interleaved 1F1B-style decode"): each device owns V
    round-robin layer chunks and activations make V trips around a
    wrapping ppermute ring, cutting the bubble from (S-1)/(M+S-1) to
    (S-1)/(V*M+S-1) — the decode-latency win when M can't be large.
    Params/cache must then be in interleaved layer order (one-time
    permutation via `interleave_layers`), and M >= S so wrapped
    activations arrive before they're consumed.
    """
    S = mesh.shape["stage"]
    B, T = tokens.shape
    if positions is None:
        positions = cache.length[:, None] + jnp.arange(T)[None, :]
    if S == 1:
        from butterfly_tpu.models.common import forward
        return forward(params, cfg, tokens, cache, positions, fresh=fresh)

    M = num_microbatches or _default_microbatches(B, S)
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if cfg.num_layers % S != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible by {S} stages")
    V = virtual_stages
    if V > 1:
        if cfg.num_layers % (S * V) != 0:
            raise ValueError(f"{cfg.num_layers} layers not divisible by "
                             f"{S} stages x {V} virtual chunks")
        if M < S:
            raise ValueError(
                f"interleaved schedule needs microbatches >= stages "
                f"({M} < {S}): a wrapped activation produced at tick "
                f"t reaches stage 0 at t+1 but is consumed at t+M-S+1")

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq)

    quant = cache.quantized
    stage_ops = (cache.k, cache.v)
    if quant:  # scale leaves stage-shard their L dim like the code leaves
        stage_ops += (cache.k_scale, cache.v_scale)
    if V > 1:
        body = partial(_interleaved_body, cfg=cfg, S=S, M=M, V=V,
                       fresh=fresh, quant=quant)
    else:
        body = partial(_pipeline_body, cfg=cfg, S=S, M=M, fresh=fresh,
                       quant=quant)
    y, new_cache = _run_gpipe(body, mesh, params["layers"], stage_ops,
                              (x, positions, mask, cos, sin), S, M, x)
    logits = final_logits(params, cfg, y)
    return logits, KVCache(new_cache[0], new_cache[1], cache.length + T,
                           *new_cache[2:])


def interleave_layers(tree, num_layers: int, S: int, V: int,
                      inverse: bool = False):
    """Permute stacked-L leaves into (or back out of) interleaved order.

    Interleaved pipeline layout: stage s's contiguous [L/S] block holds
    the round-robin chunks v*S + s for v in 0..V-1, so shard_map's
    P('stage') on the L dim gives each stage exactly its interleaved
    chunks. Apply ONCE at weight-load/cache-init — not per step.
    Leaves whose leading dim != num_layers are passed through.
    """
    import numpy as np
    Lc = num_layers // (S * V)
    order = np.asarray([(v * S + s) * Lc + i
                        for s in range(S) for v in range(V)
                        for i in range(Lc)])
    if inverse:
        inv = np.empty_like(order)
        inv[order] = np.arange(num_layers)
        order = inv

    def perm(a):
        if hasattr(a, "shape") and a.ndim >= 1 and a.shape[0] == num_layers:
            return jnp.take(a, jnp.asarray(order), axis=0)
        return a

    return jax.tree.map(perm, tree)


def _run_gpipe(body, mesh: Mesh, layers, stage_ops, rep_ops, S: int, M: int,
               x: jax.Array):
    """shard_map a GPipe body and slice the last stage's result block.

    Shared scaffolding for the contiguous and paged pipelines. Manual
    over `stage` only: layer-stacked leaves and `stage_ops` (the cache
    pytree leaves) split their leading L dim; `rep_ops` (activations,
    masks, tables) are replicated over stage; tensor/data stay auto
    (GSPMD) inside. The body's microbatch results come back stage-
    STACKED ([S*M, mb, ...], only the last stage's block meaningful)
    rather than psum-replicated: slicing that block moves ONE [B,T,D]
    activation off the last stage instead of all-reducing S zero-padded
    copies (VERDICT r2 weak item 4).
    """
    from butterfly_tpu.core import compat
    layer_in = jax.tree.map(lambda _: P("stage"), layers)
    pipe = compat.shard_map(
        body, mesh,
        in_specs=(layer_in, *([P("stage")] * len(stage_ops)),
                  *([P()] * len(rep_ops))),
        out_specs=(P("stage"), *([P("stage")] * len(stage_ops))),
        axis_names={"stage"})
    outs, *new_stage = pipe(layers, *stage_ops, *rep_ops)
    return outs[(S - 1) * M:].reshape(x.shape), tuple(new_stage)


def paged_pipeline_forward(params: Params, cfg: ModelConfig,
                           tokens: jax.Array, cache,
                           positions: Optional[jax.Array] = None,
                           active: Optional[jax.Array] = None,
                           use_kernel: bool = False, fresh: bool = False,
                           last_index: Optional[jax.Array] = None,
                           *, mesh: Mesh,
                           num_microbatches: Optional[int] = None):
    """paged_forward pipelined over `stage` (VERDICT r2 item 4).

    `last_index` is accepted for signature parity with paged_forward but
    ignored — the GPipe schedule emits full-T logits per microbatch and
    the caller gathers (engine/serving.py _prefill_slot).

    Same contract as cache.paged.paged_forward — [B,T] tokens against the
    shared page pool — but the layer stack and the pool's L dim are stage-
    sharded and microbatches of slots flow through the GPipe schedule.
    Block tables/lengths stay replicated over stage (page ownership is a
    host concept); each stage scatters/gathers only its local layers'
    pages. The Pallas kernels still engage inside the stage-manual region
    (their wrappers shard_map over the still-Auto data/tensor axes).
    """
    from butterfly_tpu.cache.paged import PagedKVCache, paged_forward
    from butterfly_tpu.models.common import (
        embed_tokens, final_logits, make_mask)

    S = mesh.shape["stage"]
    if S == 1:
        return paged_forward(params, cfg, tokens, cache, positions, active,
                             use_kernel, fresh, last_index)
    B, T = tokens.shape
    if positions is None:
        positions = cache.lengths[:, None] + jnp.arange(T)[None, :]
    if active is None:
        active = jnp.ones((B,), bool)
    M = num_microbatches or _default_microbatches(B, S)
    if B % M != 0:
        raise ValueError(f"slots {B} not divisible by microbatches {M}")
    if cfg.num_layers % S != 0:
        raise ValueError(f"{cfg.num_layers} layers not divisible by {S} stages")

    x, cos, sin = embed_tokens(params, cfg, tokens, positions)
    mask = make_mask(positions, cache.max_seq) & active[:, None, None]

    quant = cache.quantized
    stage_ops = (cache.k_pages, cache.v_pages)
    if quant:  # scale pools stage-shard their L dim like the code pools
        stage_ops += (cache.k_scale_pages, cache.v_scale_pages)
    body = partial(_paged_pipeline_body, cfg=cfg, S=S, M=M,
                   use_kernel=use_kernel, fresh=fresh, quant=quant)
    y, new_pools = _run_gpipe(
        body, mesh, params["layers"], stage_ops,
        (x, cache.page_table, positions, mask, cos, sin, active), S, M, x)
    logits = final_logits(params, cfg, y)
    new_len = jnp.where(active, cache.lengths + T, cache.lengths)
    return logits, PagedKVCache(new_pools[0], new_pools[1],
                                cache.page_table, new_len, *new_pools[2:])


def _gpipe_schedule(S: int, M: int, xs, step_fn, carry0):
    """The GPipe tick skeleton shared by the contiguous and paged bodies.

    Runs M + S - 1 ticks inside a stage-manual region; tick t has this
    stage working on microbatch m = t - stage (bubble ticks have m out of
    range). `step_fn(carry, mc, valid, inp) -> (y, carry)` runs this
    stage's local layers on one microbatch and owns all cache write-back
    masking for bubble ticks. xs is [M, mb, ...]; results are recorded
    from the last stage and returned [M, mb, ...] (garbage elsewhere —
    callers slice the last stage's block via out_specs P('stage')).
    """
    stage = lax.axis_index("stage")
    state0 = jnp.zeros_like(xs[0])
    out0 = jnp.zeros_like(xs)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(c, t):
        state, carry, outs = c
        m = t - stage
        valid = (m >= 0) & (m < M)
        mc = jnp.clip(m, 0, M - 1)
        inp = jnp.where(stage == 0, xs[jnp.clip(t, 0, M - 1)], state)
        y, carry = step_fn(carry, mc, valid, inp)
        rec = jnp.where(valid & (stage == S - 1), y, outs[mc])
        outs = lax.dynamic_update_index_in_dim(outs, rec, mc, axis=0)
        state = lax.ppermute(y, "stage", fwd_perm)
        return (state, carry, outs), None

    (_, carry, outs), _ = lax.scan(tick, (state0, carry0, out0),
                                   jnp.arange(M + S - 1))
    return outs, carry


def _paged_pipeline_body(layers, k_pages, v_pages, *ops, cfg: ModelConfig,
                         S: int, M: int, use_kernel: bool, fresh: bool,
                         quant: bool = False):
    """Per-stage GPipe body over the paged pool (manual over stage).

    layers/k_pages/v_pages (and, for int8 pools, the two scale pools that
    lead `ops`) are the local [L/S, ...] stage slice; x, the block table,
    and the per-token aux arrays are full-slot-batch and replicated over
    stage.
    """
    from butterfly_tpu.cache.paged import paged_layer_body

    if quant:
        ksp0, vsp0, x, page_table, positions, mask, cos, sin, active = ops
    else:
        x, page_table, positions, mask, cos, sin, active = ops
        ksp0 = vsp0 = None
    B = x.shape[0]
    mb = B // M

    xs = x.reshape(M, mb, *x.shape[1:])
    tbl_mb = page_table.reshape(M, mb, *page_table.shape[1:])
    pos_mb = positions.reshape(M, mb, *positions.shape[1:])
    mask_mb = mask.reshape(M, mb, *mask.shape[1:])
    cos_mb = cos.reshape(M, mb, *cos.shape[1:])
    sin_mb = sin.reshape(M, mb, *sin.shape[1:])
    act_mb = active.reshape(M, mb)

    def step(carry, mc, valid, inp):
        kp, vp, ksp, vsp = carry
        # bubble ticks redirect their pool writes to the null page via the
        # active mask (the paged analogue of the contiguous path's
        # where(valid) write-back)
        act = act_mb[mc] & valid

        def layer(x, scanned):
            lp, kpl, vpl, *scl = scanned
            out = paged_layer_body(
                x, lp, kpl, vpl, cfg=cfg, page_table=tbl_mb[mc],
                positions=pos_mb[mc], mask=mask_mb[mc], cos=cos_mb[mc],
                sin=sin_mb[mc], active=act, use_kernel=use_kernel,
                fresh=fresh, ksp=scl[0] if scl else None,
                vsp=scl[1] if scl else None)
            return out[0], tuple(out[1:])

        scan_xs = (layers, kp, vp) + ((ksp, vsp) if quant else ())
        y, new = lax.scan(layer, inp, scan_xs)
        if quant:
            return y, new
        return y, (*new, None, None)

    outs, (kp, vp, ksp, vsp) = _gpipe_schedule(
        S, M, xs, step, (k_pages, v_pages, ksp0, vsp0))
    if quant:
        return outs, kp, vp, ksp, vsp
    return outs, kp, vp


def _interleaved_body(layers, ck, cv, *ops, cfg: ModelConfig, S: int,
                      M: int, V: int, fresh: bool = False,
                      quant: bool = False):
    """Interleaved virtual-stage schedule (manual over stage).

    Work unit w = v*M + m: chunk v of microbatch m. Tick t has stage s
    on w = t - s; V*M + S - 1 ticks total. The ppermute ring WRAPS
    (S-1 -> 0): a microbatch leaving the last stage's chunk v re-enters
    stage 0 for chunk v+1. Early wrapped arrivals (they land after one
    hop but are consumed M-S+1 ticks later) sit in a per-microbatch
    buffer on stage 0. int8 caches thread their scale leaves (leading
    `ops`) through the same chunk/microbatch slicing as the code leaves.
    """
    if quant:
        ks, vs, x, positions, mask, cos, sin = ops
    else:
        x, positions, mask, cos, sin = ops
        ks = vs = None
    B = x.shape[0]
    mb = B // M
    Lc = ck.shape[0] // V  # local layers per virtual chunk

    xs = x.reshape(M, mb, *x.shape[1:])
    pos_mb = positions.reshape(M, mb, *positions.shape[1:])
    mask_mb = mask.reshape(M, mb, *mask.shape[1:])
    cos_mb = cos.reshape(M, mb, *cos.shape[1:])
    sin_mb = sin.reshape(M, mb, *sin.shape[1:])

    layers_v = jax.tree.map(lambda a: a.reshape(V, Lc, *a.shape[1:]), layers)
    cache_v = tuple(a.reshape(V, Lc, *a.shape[1:]) if a is not None else None
                    for a in (ck, cv, ks, vs))

    stage = lax.axis_index("stage")
    state0 = jnp.zeros_like(xs[0])
    buf0 = jnp.zeros_like(xs)     # stage-0 holding pen for wrapped states
    out0 = jnp.zeros_like(xs)
    ring = [(i, (i + 1) % S) for i in range(S)]

    def tick(c, t):
        state, buf, cachev, outs = c

        # bank the state that just wrapped onto stage 0 (produced by the
        # last stage at t-1 with work index t-S; destined for chunk
        # (t-S)//M + 1 of microbatch (t-S)%M)
        w_in = t - S
        keep_in = (stage == 0) & (w_in >= 0) & (w_in < V * M - M)
        m_in = jnp.clip(w_in, 0, V * M - 1) % M
        banked = lax.dynamic_update_index_in_dim(buf, state, m_in, 0)
        buf = jnp.where(keep_in, banked, buf)

        w = t - stage
        valid = (w >= 0) & (w < V * M)
        wc = jnp.clip(w, 0, V * M - 1)
        v = wc // M
        m = wc % M

        inj = jnp.where(v == 0, xs[m], buf[m])
        inp = jnp.where(stage == 0, inj, state)

        lyr = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            layers_v)
        chunk = tuple(
            None if a is None
            else lax.dynamic_index_in_dim(a, v, 0, keepdims=False)
            for a in cachev)
        mbs = tuple(
            None if a is None
            else lax.dynamic_slice_in_dim(a, m * mb, mb, axis=1)
            for a in chunk)

        y, *new = scan_layers(lyr, cfg, inp, mbs[0], mbs[1],
                              pos_mb[m], mask_mb[m], cos_mb[m],
                              sin_mb[m], fresh, mbs[2], mbs[3])
        new = tuple(new) if quant else (*new, None, None)

        def write_back(a_c, n, o):
            return lax.dynamic_update_slice_in_dim(
                a_c, jnp.where(valid, n, o), m * mb, axis=1)

        chunk = tuple(None if a is None else write_back(a, n, o)
                      for a, n, o in zip(chunk, new, mbs))
        cachev = tuple(
            None if a is None else lax.dynamic_update_index_in_dim(a, cc, v, 0)
            for a, cc in zip(cachev, chunk))

        rec = jnp.where(valid & (stage == S - 1) & (v == V - 1), y, outs[m])
        outs = lax.dynamic_update_index_in_dim(outs, rec, m, 0)
        state = lax.ppermute(y, "stage", ring)
        return (state, buf, cachev, outs), None

    (_, _, cachev, outs), _ = lax.scan(
        tick, (state0, buf0, cache_v, out0),
        jnp.arange(V * M + S - 1))
    flat = tuple(a.reshape(o.shape) for a, o in
                 zip(cachev, (ck, cv, ks, vs)) if a is not None)
    return (outs, *flat)


def _default_microbatches(B: int, S: int) -> int:
    """Largest divisor of B that is <= 2*S (keeps the bubble small without
    violating B % M == 0 for any batch size)."""
    best = 1
    for m in range(1, min(B, 2 * S) + 1):
        if B % m == 0:
            best = m
    return best


def _pipeline_body(layers, ck, cv, *ops, cfg: ModelConfig, S: int, M: int,
                   fresh: bool = False, quant: bool = False):
    """Per-stage GPipe body, contiguous cache (manual over stage).

    layers/ck/cv (and, for int8 caches, the two scale leaves that lead
    `ops`) are the local [L/S, ...] stage slice; x [B,T,D] etc. are
    full-batch and replicated over stage. Returns outs stage-stacked
    (real results only on the last stage — out_specs P('stage'), caller
    slices — no [B,T,D] all-reduce over `stage`).
    """
    if quant:
        ks0, vs0, x, positions, mask, cos, sin = ops
    else:
        x, positions, mask, cos, sin = ops
        ks0 = vs0 = None
    B = x.shape[0]
    mb = B // M

    # [M, mb, ...] microbatch views
    xs = x.reshape(M, mb, *x.shape[1:])
    pos_mb = positions.reshape(M, mb, *positions.shape[1:])
    mask_mb = mask.reshape(M, mb, *mask.shape[1:])
    cos_mb = cos.reshape(M, mb, *cos.shape[1:])
    sin_mb = sin.reshape(M, mb, *sin.shape[1:])

    def step(carry, mc, valid, inp):
        ck, cv, ks, vs = carry
        sl = lambda a: lax.dynamic_slice_in_dim(a, mc * mb, mb, axis=1)
        ck_m, cv_m = sl(ck), sl(cv)
        ks_m = sl(ks) if quant else None
        vs_m = sl(vs) if quant else None

        y, nk, nv, *nsc = scan_layers(layers, cfg, inp, ck_m, cv_m,
                                      pos_mb[mc], mask_mb[mc], cos_mb[mc],
                                      sin_mb[mc], fresh, ks_m, vs_m)

        # write back cache only on valid (non-bubble) ticks
        upd = lambda a, n, o: lax.dynamic_update_slice_in_dim(
            a, jnp.where(valid, n, o), mc * mb, axis=1)
        ck = upd(ck, nk, ck_m)
        cv = upd(cv, nv, cv_m)
        if quant:
            ks = upd(ks, nsc[0], ks_m)
            vs = upd(vs, nsc[1], vs_m)
        return y, (ck, cv, ks, vs)

    outs, (ck, cv, ks, vs) = _gpipe_schedule(S, M, xs, step,
                                             (ck, cv, ks0, vs0))
    if quant:
        return outs, ck, cv, ks, vs
    return outs, ck, cv
