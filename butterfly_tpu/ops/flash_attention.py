"""Pallas flash attention (TPU/Mosaic): blockwise causal self-attention.

The prefill-side hot kernel (SURVEY.md §2.2 C4/C5 "hand-written kernels go
in Pallas — the TPU-idiomatic replacement for the CUDA kernels the north
star attributes to the original design"). Design:

* grid (B, Nq, Tq/BQ, S/BK); the last axis is a reduction ("arbitrary")
  dimension — the out block's index map ignores it, so the same out tile
  stays VMEM-resident while K/V blocks stream through, and the online-
  softmax state (m, l, acc f32 scratch) carries across it.
* Causality works on absolute positions (q_pos >= k_pos); blocks entirely
  in the future contribute nothing (their exp() underflows to 0 via the
  -inf mask — no branch divergence, MXU stays busy on the diagonal).
* GQA: q head n reads k/v head n // (Nq/Kv) via the k/v index maps — no
  materialized head broadcast.
* Warm-prefix prefill (ISSUE 13): chunk continuations / prefix-cache
  resumes hand the kernel the CACHED context (a gathered pool view or a
  contiguous cache slice, float or int8 codes + scales) as extra
  reduction-axis blocks AHEAD of the causal fresh-chunk blocks, per-row
  count-masked at the scalar-prefetched `start` — the append-to-KV-
  history attention shape online softmax was built for, replacing the
  dense O(T*S) warm fallback.
* Off-TPU the wrapper runs the same kernel in interpreter mode, so CPU
  tests validate the exact kernel code path numerics.

Used by the engine for fresh AND warm multi-token prefills
(cfg.attn_impl="flash"); decode-side paged attention lives in
ops/paged_attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 names it TPUCompilerParams; alias so both resolve (the
# interpret-mode CPU tests otherwise die before interpretation starts)
if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30


def _block_update(s, mask, vf, m_ref, l_ref, acc_ref, vs_row=None):
    """One online-softmax accumulation step shared by the fresh-chunk
    blocks and the cached-prefix segment (the same recurrence
    ops/paged_attention.py uses for its page/window blocks): s [BQ, C]
    raw scores, mask [BQ, C] (True = attend), vf [C, H] values, vs_row
    optional [1, C] V scales folded into the probs (int8 prefix)."""
    s = jnp.where(mask, s, NEG_INF)
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    if vs_row is not None:
        p = p * vs_row                                 # V scale into probs
    acc_ref[:] = acc_ref[:] * corr + jnp.dot(
        p, vf, preferred_element_type=jnp.float32)
    m_ref[:] = m_new


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, bq: int, bk: int, seq_len: int, causal: bool):
    i = pl.program_id(2)          # q block
    j = pl.program_id(3)          # k block (reduction axis)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)            # [BQ, H]
    k = k_ref[0, 0].astype(jnp.float32)            # [BK, H]
    v = v_ref[0, 0].astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = k_pos < seq_len                          # padded keys
    if causal:
        mask = mask & (q_pos >= k_pos)
    _block_update(s, mask, v, m_ref, l_ref, acc_ref)

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _flash_warm_kernel(start_ref, q_ref, k_ref, v_ref, *rest,
                       bq: int, bk: int, bp: int, np_blocks: int,
                       seq_len: int, quant: bool):
    """Warm-prefix flash prefill kernel (ISSUE 13): the reduction axis
    runs `np_blocks` cached-prefix blocks — read from the contiguous
    cache view, masked per row by the scalar-prefetched `start` (the
    count of live cached tokens; garbage past it never contributes) —
    AHEAD of the causal fresh-chunk blocks, all sharing one
    online-softmax state (`_block_update`, PR 12's window-segment
    pattern). Every valid prefix position precedes every query's
    absolute position (queries sit at start..start+T-1), so the prefix
    needs only the `< start` count mask, no causal triangle. Blocks
    entirely past a row's `start` skip their compute via `pl.when`
    (the DMA still runs, like the paged kernel's dead-page blocks).

    quant: the prefix arrives as int8 codes with per-vector scales
    (the pool representation) — K scales multiply the score columns
    output-side, V scales fold into the probs, exactly like
    models.common.attend / the paged kernel's int8 blocks. The fresh
    chunk is always float (the caller mirrors the cache's
    quantize-dequantize there for operand parity with the dense path).
    """
    pk_ref, pv_ref, *rest = rest
    pks_ref = pvs_ref = None
    if quant:
        pks_ref, pvs_ref, *rest = rest
    o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    i = pl.program_id(2)          # q block
    j = pl.program_id(3)          # reduction axis: prefix then fresh
    nj = pl.num_programs(3)
    start = start_ref[b]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when((j < np_blocks) & (j * bp < start))
    def _prefix():
        q = q_ref[0, 0].astype(jnp.float32)        # [BQ, H]
        kf = pk_ref[0, 0].astype(jnp.float32)      # [BP, H]
        vf = pv_ref[0, 0].astype(jnp.float32)
        scale = jax.lax.rsqrt(jnp.asarray(q.shape[-1], jnp.float32))
        s = jnp.dot(q, kf.T, preferred_element_type=jnp.float32)
        if quant:
            s = s * pks_ref[0, 0]                  # [1, BP] K scale columns
        s = s * scale
        cols = j * bp + jax.lax.broadcasted_iota(jnp.int32, (bq, bp), 1)
        mask = cols < start
        _block_update(s, mask, vf, m_ref, l_ref, acc_ref,
                      pvs_ref[0, 0] if quant else None)

    @pl.when(j >= np_blocks)
    def _fresh():
        jf = j - np_blocks
        q = q_ref[0, 0].astype(jnp.float32)
        kf = k_ref[0, 0].astype(jnp.float32)
        vf = v_ref[0, 0].astype(jnp.float32)
        scale = jax.lax.rsqrt(jnp.asarray(q.shape[-1], jnp.float32))
        s = jnp.dot(q, kf.T, preferred_element_type=jnp.float32) * scale
        # chunk-relative causality: absolute positions share the row's
        # start offset, so the relative triangle is exact
        q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_pos = jf * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (k_pos < seq_len) & (q_pos >= k_pos)
        _block_update(s, mask, vf, m_ref, l_ref, acc_ref)

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _auto_axes(mesh) -> set:
    """Axis names of the ambient mesh still under GSPMD (Auto) control."""
    from jax.sharding import AxisType
    return {n for n, t in zip(mesh.axis_names, mesh.axis_types)
            if t == AxisType.Auto}


def _abstract_mesh():
    """The ambient abstract mesh, or None on jax < 0.5: 0.4.x has no
    jax.sharding.get_abstract_mesh — and no jax.set_mesh to install an
    ambient mesh in the first place, so "no mesh" is the truth there,
    not a guess. Same compat class as the TPUCompilerParams alias above
    (without it, every use_kernels serving path dies on 0.4.37 before
    a single kernel runs)."""
    get = getattr(jax.sharding, "get_abstract_mesh", None)
    return get() if get is not None else None


def shardable_axes(batch: int, nq: int, kv: int):
    """(data_axis, tensor_axis) of the ambient mesh usable to shard an
    attention operand set: `data` must divide the batch/slot dim, `tensor`
    must divide both head counts; an axis is skipped when absent, size 1,
    or already Manual from an enclosing shard_map (e.g. the pipeline's
    `stage`). Shared eligibility rule for both kernel wrappers."""
    mesh = _abstract_mesh()
    if mesh is None or mesh.empty:
        return None, None
    auto = _auto_axes(mesh)
    d = "data" if ("data" in auto and mesh.shape["data"] > 1
                   and batch % mesh.shape["data"] == 0) else None
    t = "tensor" if ("tensor" in auto and mesh.shape["tensor"] > 1
                     and nq % mesh.shape["tensor"] == 0
                     and kv % mesh.shape["tensor"] == 0) else None
    return d, t


def live_auto_mesh() -> bool:
    """True when the ambient mesh has any multi-device axis still under
    GSPMD (Auto) control — a bare pallas_call traced there would be an
    opaque custom call the partitioner can't shard."""
    mesh = _abstract_mesh()
    if mesh is None or mesh.empty:
        return False
    return any(mesh.shape[n] > 1 for n in _auto_axes(mesh))


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            causal: bool = True,
                            prefix_k: jax.Array = None,
                            prefix_v: jax.Array = None,
                            prefix_len: jax.Array = None,
                            prefix_k_scale: jax.Array = None,
                            prefix_v_scale: jax.Array = None) -> jax.Array:
    """Mesh-aware flash attention (SURVEY.md §7 stages 4/6).

    A pallas_call is an opaque custom call GSPMD cannot partition, so under
    an active mesh we wrap the kernel in `shard_map` over the axes whose
    sharding the partitioner gave these operands: batch over `data`, heads
    over `tensor` (parallel/partition.py puts q-heads/kv-heads there via
    the column-parallel wq/wk/wv). Attention is purely local to a
    (batch, head) shard — each shard runs the unmodified kernel on its
    slice, no collectives. Axes that don't divide (or are already Manual
    from an enclosing shard_map, e.g. the pipeline's `stage`) are left
    alone; with no mesh at all this is exactly `flash_attention`.

    Returns None when a live multi-device Auto mesh is present but no
    axis can shard the operands: the caller MUST fall back to its dense
    path there (a bare pallas_call under GSPMD is an opaque custom call
    — the failure mode the engines' old mesh-disables-kernels guard
    existed to prevent).

    Warm-prefix prefill (ISSUE 13): prefix_k/prefix_v + prefix_len give
    the kernel a cached-context segment ahead of the fresh chunk (see
    flash_attention). The cache/scale operands shard on the same axes —
    batch/slots over `data`, kv heads over `tensor`
    (parallel/partition.py warm_prefix_specs, matching the pool
    sharding paged_cache_specs assigns).

    Mixed-dispatch note (ISSUE 18): the fused mixed block does NOT call
    this prefill entry point — inside the scan every lane (decode OR
    prefill chunk) attends through the per-step paged/window attention
    of the decode program, with per-slot lengths/cursors doing the
    masking. This kernel remains the ALTERNATING path's chunked-prefill
    engine (`mixed_dispatch=False`, or a stateful draft source's
    automatic fallback).
    """
    from jax.sharding import PartitionSpec as P

    B, T, Nq, H = q.shape
    Kv = k.shape[2]
    d, t = shardable_axes(B, Nq, Kv)
    if d is None and t is None:
        if live_auto_mesh():
            return None
        return flash_attention(q, k, v, causal=causal,
                               prefix_k=prefix_k, prefix_v=prefix_v,
                               prefix_len=prefix_len,
                               prefix_k_scale=prefix_k_scale,
                               prefix_v_scale=prefix_v_scale)
    spec = P(d, None, t, None)
    if prefix_k is None:
        fn = jax.shard_map(
            functools.partial(flash_attention, causal=causal),
            in_specs=(spec, spec, spec), out_specs=spec,
            axis_names={a for a in (d, t) if a is not None},
            check_vma=False)
        return fn(q, k, v)
    # lazy: partition imports models.common at module level, which now
    # imports this module — an import here would close the cycle
    from butterfly_tpu.parallel.partition import warm_prefix_specs
    quant = prefix_k_scale is not None
    args = [q, k, v, prefix_k, prefix_v, prefix_len]
    if quant:
        args += [prefix_k_scale, prefix_v_scale]

    def _warm(q, k, v, pk, pv, plen, *scales):
        kw = {}
        if scales:
            kw = dict(prefix_k_scale=scales[0], prefix_v_scale=scales[1])
        return flash_attention(q, k, v, causal=causal, prefix_k=pk,
                               prefix_v=pv, prefix_len=plen, **kw)

    fn = jax.shard_map(
        _warm,
        in_specs=(spec, spec, spec) + warm_prefix_specs(d, t, quant),
        out_specs=spec,
        axis_names={a for a in (d, t) if a is not None}, check_vma=False)
    return fn(*args)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: bool | None = None,
                    prefix_k: jax.Array = None,
                    prefix_v: jax.Array = None,
                    prefix_len: jax.Array = None,
                    prefix_k_scale: jax.Array = None,
                    prefix_v_scale: jax.Array = None) -> jax.Array:
    """Blockwise (flash) attention over fresh Q/K/V.

    q: [B, T, Nq, H]; k/v: [B, T, Kv, H] (same T: self-attention).
    Returns [B, T, Nq, H] in q.dtype. Softmax/accum in f32.

    Warm-prefix prefill (ISSUE 13): prefix_k/prefix_v hand the kernel a
    CACHED-CONTEXT segment attended ahead of the (causal) fresh chunk —
    the append-to-KV-history shape chunked/warm prefill needs, in the
    same representation models.common.attend consumes:

    * float view [B, Sp, Kv, H] (a gathered pool view or a contiguous
      cache slice), or
    * int8 codes [B, Kv, Sp, H] with per-vector scales
      prefix_k_scale/prefix_v_scale [B, Kv, Sp] dequantized in-kernel.

    prefix_len [B] int32 is each row's live cached-token count
    (scalar-prefetched; positions at or past it — recycled-buffer
    garbage, batch padding rows, the chunk's own already-written copy —
    never contribute). Queries sit at absolute positions
    prefix_len[b] + 0..T-1, so `causal` must be True.
    """
    B, T, Nq, H = q.shape
    Kv = k.shape[2]
    G = Nq // Kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Block shapes must keep the sublane dim a multiple of 8 for Mosaic
    # lowering on real TPU (odd T like 20 would otherwise produce 20xH
    # blocks); padding below already handles T < block.
    bq = min(block_q, -(-max(T, 8) // 8) * 8)
    bk = min(block_k, -(-max(T, 8) // 8) * 8)
    Tq = -(-T // bq) * bq
    Tk = -(-T // bk) * bk

    qt = jnp.moveaxis(q, 2, 1)                      # [B, Nq, T, H]
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    qt = jnp.pad(qt, ((0, 0), (0, 0), (0, Tq - T), (0, 0)))
    kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Tk - T), (0, 0)))
    vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Tk - T), (0, 0)))

    if prefix_k is not None:
        if not causal:
            raise ValueError("warm-prefix flash attention is causal-only")
        out = _flash_warm_call(qt, kt, vt, prefix_k, prefix_v, prefix_len,
                               prefix_k_scale, prefix_v_scale, T=T, bq=bq,
                               bk=bk, block_k=block_k, G=G,
                               interpret=interpret)
        return jnp.moveaxis(out[:, :, :T, :], 1, 2)  # [B, T, Nq, H]

    grid = (B, Nq, Tq // bq, Tk // bk)
    kernel = functools.partial(_flash_kernel, bq=bq, bk=bk, seq_len=T,
                               causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, H), lambda b, n, i, j: (b, n, i, 0)),
            pl.BlockSpec((1, 1, bk, H),
                         lambda b, n, i, j, G=G: (b, n // G, j, 0)),
            pl.BlockSpec((1, 1, bk, H),
                         lambda b, n, i, j, G=G: (b, n // G, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, H),
                               lambda b, n, i, j: (b, n, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Nq, Tq, H), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running denom
            pltpu.VMEM((bq, H), jnp.float32),       # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.moveaxis(out[:, :, :T, :], 1, 2)     # [B, T, Nq, H]


def _flash_warm_call(qt, kt, vt, prefix_k, prefix_v, prefix_len,
                     prefix_k_scale, prefix_v_scale, *, T: int, bq: int,
                     bk: int, block_k: int, G: int, interpret: bool):
    """Build + dispatch the warm-prefix pallas_call. qt/kt/vt arrive
    head-major and padded ([B, N, Tq/Tk, H]); returns [B, Nq, Tq, H].

    The prefix canonicalizes to kv-major [B, Kv, Sp, H] (the int8 pool
    order; the float view moveaxes into it, the same relayout the q/k/v
    operands already pay) and pads Sp to the prefix block. The per-row
    `start` vector rides as the one scalar-prefetch operand so the
    BlockSpec index maps and the in-kernel masks see it before the body
    runs (the paged kernel's PrefetchScalarGridSpec pattern)."""
    B, Nq, Tq, H = qt.shape
    Kv = kt.shape[1]
    quant = prefix_k_scale is not None
    if quant:
        pk, pv = prefix_k, prefix_v            # [B, Kv, Sp, H] codes
    else:
        pk = jnp.moveaxis(prefix_k, 2, 1)      # [B, Sp, Kv, H] -> kv-major
        pv = jnp.moveaxis(prefix_v, 2, 1)
    Sp = pk.shape[2]
    bp = min(block_k, -(-max(Sp, 8) // 8) * 8)
    Sp_pad = -(-Sp // bp) * bp
    np_blocks = Sp_pad // bp
    nf = kt.shape[2] // bk
    pk = jnp.pad(pk, ((0, 0), (0, 0), (0, Sp_pad - Sp), (0, 0)))
    pv = jnp.pad(pv, ((0, 0), (0, 0), (0, Sp_pad - Sp), (0, 0)))

    def q_map(b, n, i, j, st):
        return (b, n, i, 0)

    def k_map(b, n, i, j, st):
        # prefix steps clamp to fresh block 0 (DMA runs, block unused)
        return (b, n // G, jnp.clip(j - np_blocks, 0, nf - 1), 0)

    def p_map(b, n, i, j, st):
        # fresh steps clamp to the last prefix block (unused)
        return (b, n // G, jnp.minimum(j, np_blocks - 1), 0)

    def ps_map(b, n, i, j, st):
        return (b, n // G, 0, jnp.minimum(j, np_blocks - 1))

    in_specs = [
        pl.BlockSpec((1, 1, bq, H), q_map),
        pl.BlockSpec((1, 1, bk, H), k_map),
        pl.BlockSpec((1, 1, bk, H), k_map),
        pl.BlockSpec((1, 1, bp, H), p_map),
        pl.BlockSpec((1, 1, bp, H), p_map),
    ]
    args = [qt, kt, vt, pk, pv]
    if quant:
        # [B, Kv, Sp] -> [B, Kv, 1, Sp] (free bitcast): a (1, 1, bp)
        # block of the 3-D array would put a size-1 sublane against Kv;
        # (1, 1, 1, bp) of the 4-D form matches the array (the paged
        # kernel's flat-scale-row trick)
        pks = jnp.pad(prefix_k_scale, ((0, 0), (0, 0), (0, Sp_pad - Sp)))
        pvs = jnp.pad(prefix_v_scale, ((0, 0), (0, 0), (0, Sp_pad - Sp)))
        in_specs += [
            pl.BlockSpec((1, 1, 1, bp), ps_map),
            pl.BlockSpec((1, 1, 1, bp), ps_map),
        ]
        args += [pks.reshape(B, Kv, 1, Sp_pad),
                 pvs.reshape(B, Kv, 1, Sp_pad)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, Nq, Tq // bq, np_blocks + nf),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, bq, H), q_map),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running denom
            pltpu.VMEM((bq, H), jnp.float32),       # accumulator
        ],
    )
    kernel = functools.partial(_flash_warm_kernel, bq=bq, bk=bk, bp=bp,
                               np_blocks=np_blocks, seq_len=T, quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Nq, Tq, H), qt.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(prefix_len.astype(jnp.int32), *args)
