"""Pallas flash attention (TPU/Mosaic): blockwise causal self-attention.

The prefill-side hot kernel (SURVEY.md §2.2 C4/C5 "hand-written kernels go
in Pallas — the TPU-idiomatic replacement for the CUDA kernels the north
star attributes to the original design"). Design:

* grid (B, Nq, Tq/BQ, S/BK); the last axis is a reduction ("arbitrary")
  dimension — the out block's index map ignores it, so the same out tile
  stays VMEM-resident while K/V blocks stream through, and the online-
  softmax state (m, l, acc f32 scratch) carries across it.
* Causality works on absolute positions (q_pos >= k_pos); blocks entirely
  in the future contribute nothing (their exp() underflows to 0 via the
  -inf mask — no branch divergence, MXU stays busy on the diagonal).
* GQA: q head n reads k/v head n // (Nq/Kv) via the k/v index maps — no
  materialized head broadcast.
* Off-TPU the wrapper runs the same kernel in interpreter mode, so CPU
  tests validate the exact kernel code path numerics.

Used by the engine for fresh prefills (cfg.attn_impl="flash"); decode-side
paged attention lives in ops/paged_attention.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 names it TPUCompilerParams; alias so both resolve (the
# interpret-mode CPU tests otherwise die before interpretation starts)
if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, bq: int, bk: int, seq_len: int, causal: bool):
    i = pl.program_id(2)          # q block
    j = pl.program_id(3)          # k block (reduction axis)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)            # [BQ, H]
    k = k_ref[0, 0].astype(jnp.float32)            # [BK, H]
    v = v_ref[0, 0].astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

    q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = k_pos < seq_len                          # padded keys
    if causal:
        mask = mask & (q_pos >= k_pos)
    s = jnp.where(mask, s, NEG_INF)

    m_prev, l_prev = m_ref[:], l_ref[:]
    m_blk = jnp.max(s, axis=-1, keepdims=True)      # [BQ, 1]
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(s - m_new)
    p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr + jnp.dot(
        p, v, preferred_element_type=jnp.float32)
    m_ref[:] = m_new
    l_ref[:] = l_new

    @pl.when(j == nk - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[:] /
                       jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _auto_axes(mesh) -> set:
    """Axis names of the ambient mesh still under GSPMD (Auto) control."""
    from jax.sharding import AxisType
    return {n for n, t in zip(mesh.axis_names, mesh.axis_types)
            if t == AxisType.Auto}


def shardable_axes(batch: int, nq: int, kv: int):
    """(data_axis, tensor_axis) of the ambient mesh usable to shard an
    attention operand set: `data` must divide the batch/slot dim, `tensor`
    must divide both head counts; an axis is skipped when absent, size 1,
    or already Manual from an enclosing shard_map (e.g. the pipeline's
    `stage`). Shared eligibility rule for both kernel wrappers."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return None, None
    auto = _auto_axes(mesh)
    d = "data" if ("data" in auto and mesh.shape["data"] > 1
                   and batch % mesh.shape["data"] == 0) else None
    t = "tensor" if ("tensor" in auto and mesh.shape["tensor"] > 1
                     and nq % mesh.shape["tensor"] == 0
                     and kv % mesh.shape["tensor"] == 0) else None
    return d, t


def live_auto_mesh() -> bool:
    """True when the ambient mesh has any multi-device axis still under
    GSPMD (Auto) control — a bare pallas_call traced there would be an
    opaque custom call the partitioner can't shard."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return False
    return any(mesh.shape[n] > 1 for n in _auto_axes(mesh))


def flash_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                            causal: bool = True) -> jax.Array:
    """Mesh-aware flash attention (SURVEY.md §7 stages 4/6).

    A pallas_call is an opaque custom call GSPMD cannot partition, so under
    an active mesh we wrap the kernel in `shard_map` over the axes whose
    sharding the partitioner gave these operands: batch over `data`, heads
    over `tensor` (parallel/partition.py puts q-heads/kv-heads there via
    the column-parallel wq/wk/wv). Attention is purely local to a
    (batch, head) shard — each shard runs the unmodified kernel on its
    slice, no collectives. Axes that don't divide (or are already Manual
    from an enclosing shard_map, e.g. the pipeline's `stage`) are left
    alone; with no mesh at all this is exactly `flash_attention`.

    Returns None when a live multi-device Auto mesh is present but no
    axis can shard the operands: the caller MUST fall back to its dense
    path there (a bare pallas_call under GSPMD is an opaque custom call
    — the failure mode the engines' old mesh-disables-kernels guard
    existed to prevent).
    """
    from jax.sharding import PartitionSpec as P

    B, T, Nq, H = q.shape
    Kv = k.shape[2]
    d, t = shardable_axes(B, Nq, Kv)
    if d is None and t is None:
        if live_auto_mesh():
            return None
        return flash_attention(q, k, v, causal=causal)
    spec = P(d, None, t, None)
    fn = jax.shard_map(
        functools.partial(flash_attention, causal=causal),
        in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={a for a in (d, t) if a is not None}, check_vma=False)
    return fn(q, k, v)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128,
                    interpret: bool | None = None) -> jax.Array:
    """Blockwise (flash) attention over fresh Q/K/V.

    q: [B, T, Nq, H]; k/v: [B, T, Kv, H] (same T: self-attention).
    Returns [B, T, Nq, H] in q.dtype. Softmax/accum in f32.
    """
    B, T, Nq, H = q.shape
    Kv = k.shape[2]
    G = Nq // Kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # Block shapes must keep the sublane dim a multiple of 8 for Mosaic
    # lowering on real TPU (odd T like 20 would otherwise produce 20xH
    # blocks); padding below already handles T < block.
    bq = min(block_q, -(-max(T, 8) // 8) * 8)
    bk = min(block_k, -(-max(T, 8) // 8) * 8)
    Tq = -(-T // bq) * bq
    Tk = -(-T // bk) * bk

    qt = jnp.moveaxis(q, 2, 1)                      # [B, Nq, T, H]
    kt = jnp.moveaxis(k, 2, 1)
    vt = jnp.moveaxis(v, 2, 1)
    qt = jnp.pad(qt, ((0, 0), (0, 0), (0, Tq - T), (0, 0)))
    kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Tk - T), (0, 0)))
    vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Tk - T), (0, 0)))

    grid = (B, Nq, Tq // bq, Tk // bk)
    kernel = functools.partial(_flash_kernel, bq=bq, bk=bk, seq_len=T,
                               causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, H), lambda b, n, i, j: (b, n, i, 0)),
            pl.BlockSpec((1, 1, bk, H),
                         lambda b, n, i, j, G=G: (b, n // G, j, 0)),
            pl.BlockSpec((1, 1, bk, H),
                         lambda b, n, i, j, G=G: (b, n // G, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, H),
                               lambda b, n, i, j: (b, n, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Nq, Tq, H), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),       # running max
            pltpu.VMEM((bq, 1), jnp.float32),       # running denom
            pltpu.VMEM((bq, H), jnp.float32),       # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.moveaxis(out[:, :, :T, :], 1, 2)     # [B, T, Nq, H]
