"""Pallas ring-attention inner kernel: partial flash stats + merges.

The seq-parallel paths (parallel/sequence.py) used plain jnp einsums
for every K/V block a ring step visits — a full dense score matrix per
step, no online softmax (ISSUE 20). This module factors the per-block
work into the SAME flash-attention recurrence the prefill kernel uses
(`ops/flash_attention.py:_block_update`), exposed as *partial,
unnormalized* statistics so ring steps compose:

    stats = (m [B,Nq,T], l [B,Nq,T], acc [B,Nq,T,H])   all f32

where for the keys visited so far  m = max score,  l = sum exp(s - m),
acc = sum exp(s - m) * v.  Two partials merge associatively
(`merge_stats`) and a final `finalize_stats` normalizes — the standard
online-softmax decomposition, so the ring loop (and the decode path's
cross-device pmax/psum reduction) never rescales V accumulators by a
denominator until every block has been seen.

Masking contract (single mask, no per-case wheres): the only in-block
predicate is  k_pos <= q_pos.  Callers sanitize invalid key positions
(padding, beyond the live prefix, unwritten suffix slots) to
`INVALID_POS` (int32 max) so one causal comparison covers causality,
raggedness and padding at once. Masked-out rows produce m = NEG_INF
(a FINITE -1e30, never -inf), l = 0, acc = 0 — every merge identity
then needs no isinf/NaN guards: exp(NEG_INF - anything) underflows to
an honest 0.

int8: K/V may arrive as pool-representation codes [B,Kv,S,H] with
per-vector scales [B,Kv,S]; the K scale multiplies score columns
output-side and the V scale folds into the probs (dequant-in-kernel,
exactly the warm-prefix flash segment / models.common.attend order).

Two legs with one contract:

* `ring_block_stats` — the Pallas kernel (grid (B, Nq, Tq/bq, S/bk),
  reduction axis "arbitrary", VMEM f32 scratch). Off-TPU it runs in
  interpreter mode so CPU tests cover the exact kernel numerics.
* `ring_block_stats_ref` — the jnp twin, the jax-0.4.37 / CPU
  fallback inside shard_map and the parity reference.

`block_stats` dispatches between them on the backend.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from butterfly_tpu.ops.flash_attention import NEG_INF, _block_update

#: sanitized "never attend" key position: k_pos <= q_pos is False for
#: every real query position.
INVALID_POS = 2**31 - 1


# ---------------------------------------------------------------------------
# Stats algebra (shared by both legs and the ring/decode merges)
# ---------------------------------------------------------------------------

def zero_stats(B: int, Nq: int, T: int, H: int):
    """Identity element of `merge_stats` (m = finite NEG_INF)."""
    return (jnp.full((B, Nq, T), NEG_INF, jnp.float32),
            jnp.zeros((B, Nq, T), jnp.float32),
            jnp.zeros((B, Nq, T, H), jnp.float32))


def merge_stats(a, b):
    """Merge two partial flash stats over disjoint key sets.

    The running-max correction: both accumulators rescale from their
    own max to the joint max before adding. m is always >= NEG_INF
    (finite), so the exps are well-defined with no isneginf guard —
    a fully-masked partial (m = NEG_INF, l = acc = 0) merges as a
    clean no-op.
    """
    m_a, l_a, acc_a = a
    m_b, l_b, acc_b = b
    m = jnp.maximum(m_a, m_b)
    c_a = jnp.exp(m_a - m)
    c_b = jnp.exp(m_b - m)
    l = l_a * c_a + l_b * c_b
    acc = acc_a * c_a[..., None] + acc_b * c_b[..., None]
    return m, l, acc


def finalize_stats(stats, dtype):
    """Normalize merged stats -> [B, T, Nq, H] attention output."""
    _, l, acc = stats
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.moveaxis(out, 1, 2).astype(dtype)     # [B,Nq,T,H]->[B,T,Nq,H]


def block_stats(q, k, v, q_pos, k_pos, k_scale=None, v_scale=None,
                kernel=None):
    """Backend dispatch: Pallas kernel on TPU, jnp twin elsewhere.

    The twin is not a stopgap — it is the jax-0.4.37/CPU fallback the
    shard_map bodies rely on (interpret-mode pallas inside shard_map
    is both slow and version-fragile); the kernel leg is covered on
    CPU by calling `ring_block_stats` directly in interpreter mode
    (tests/test_longctx.py parity grid).
    """
    if kernel is None:
        kernel = jax.default_backend() == "tpu"
    if kernel:
        return ring_block_stats(q, k, v, q_pos, k_pos, k_scale, v_scale)
    return ring_block_stats_ref(q, k, v, q_pos, k_pos, k_scale, v_scale)


# ---------------------------------------------------------------------------
# jnp twin (reference + fallback)
# ---------------------------------------------------------------------------

def ring_block_stats_ref(q, k, v, q_pos, k_pos, k_scale=None, v_scale=None):
    """jnp reference for one K/V block's partial flash stats.

    q: [B,T,Nq,H]; float k/v: [B,S,Kv,H]; int8 k/v: codes [B,Kv,S,H]
    with k_scale/v_scale [B,Kv,S]. q_pos [B,T], k_pos [B,S] int32 —
    invalid keys sanitized to INVALID_POS. Returns (m, l, acc) as
    [B,Nq,T] / [B,Nq,T] / [B,Nq,T,H] f32, head order n = kv*G + g
    (matches the kernel's n // G head map).
    """
    B, T, Nq, H = q.shape
    quant = k_scale is not None
    Kv = k.shape[1] if quant else k.shape[2]
    G = Nq // Kv
    scale = 1.0 / jnp.sqrt(jnp.asarray(H, jnp.float32))
    qh = jnp.moveaxis(q, 2, 1).reshape(B, Kv, G, T, H)
    kf = k.astype(jnp.float32) if quant else \
        jnp.moveaxis(k, 2, 1).astype(jnp.float32)    # [B,Kv,S,H]
    vf = v.astype(jnp.float32) if quant else \
        jnp.moveaxis(v, 2, 1).astype(jnp.float32)
    s = jnp.einsum("bkgth,bksh->bkgts", qh.astype(jnp.float32), kf,
                   preferred_element_type=jnp.float32)
    if quant:
        s = s * k_scale[:, :, None, None, :]
    s = s * scale
    mask = k_pos[:, None, None, None, :] <= q_pos[:, None, None, :, None]
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)                           # [B,Kv,G,T] finite
    p = jnp.where(mask, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    if quant:
        p = p * v_scale[:, :, None, None, :]
    acc = jnp.einsum("bkgts,bksh->bkgth", p, vf,
                     preferred_element_type=jnp.float32)
    return (m.reshape(B, Nq, T), l.reshape(B, Nq, T),
            acc.reshape(B, Nq, T, H))


# ---------------------------------------------------------------------------
# Pallas kernel leg
# ---------------------------------------------------------------------------

def _ring_kernel(q_ref, k_ref, v_ref, qp_ref, kp_ref, *rest,
                 quant: bool):
    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref, *rest = rest
    m_ref, l_ref, acc_ref, m_sc, l_sc, acc_sc = rest
    j = pl.program_id(3)          # k block (reduction axis)
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q = q_ref[0, 0].astype(jnp.float32)              # [BQ, H]
    kf = k_ref[0, 0].astype(jnp.float32)             # [BK, H]
    vf = v_ref[0, 0].astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.dot(q, kf.T, preferred_element_type=jnp.float32)
    vs_row = None
    if quant:
        s = s * ks_ref[0, 0]                         # [1, BK] K scale cols
        vs_row = vs_ref[0, 0]
    s = s * scale
    # the ONE mask: sanitized positions (INVALID_POS keys never pass)
    mask = kp_ref[0, 0] <= qp_ref[0, 0]              # [1,BK] vs [BQ,1]
    _block_update(s, mask, vf, m_sc, l_sc, acc_sc, vs_row)

    @pl.when(j == nk - 1)
    def _out():
        # scratch m is >= NEG_INF (finite) once any block ran: masked
        # scores are NEG_INF, not -inf, so max() lifts off the -inf init
        m_ref[0, 0] = m_sc[:]
        l_ref[0, 0] = l_sc[:]
        acc_ref[0, 0] = acc_sc[:]


def ring_block_stats(q, k, v, q_pos, k_pos, k_scale=None, v_scale=None,
                     block_q: int = 128, block_k: int = 128,
                     interpret=None):
    """Pallas leg: same contract as `ring_block_stats_ref`.

    Grid (B, Nq, Tq/bq, S/bk); the last axis streams K/V blocks through
    one VMEM-resident online-softmax state per q tile (the
    flash-attention layout), but writes out raw (m, l, acc) instead of
    normalizing — ring merges happen outside. Positions ride as int32
    planes ([B,1,Tq,1] / [B,1,1,S] so their blocks are 2-D tiles, the
    warm kernel's 4-D scale-row trick); key padding is sanitized to
    INVALID_POS here, so callers only sanitize semantic invalidity.
    """
    B, T, Nq, H = q.shape
    quant = k_scale is not None
    Kv = k.shape[1] if quant else k.shape[2]
    S = k.shape[2] if quant else k.shape[1]
    G = Nq // Kv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(block_q, -(-max(T, 8) // 8) * 8)
    bk = min(block_k, -(-max(S, 8) // 8) * 8)
    Tq = -(-T // bq) * bq
    Tk = -(-S // bk) * bk

    qt = jnp.pad(jnp.moveaxis(q, 2, 1), ((0, 0), (0, 0), (0, Tq - T), (0, 0)))
    if quant:
        kt, vt = k, v                                 # already kv-major
    else:
        kt = jnp.moveaxis(k, 2, 1)                    # [B, Kv, S, H]
        vt = jnp.moveaxis(v, 2, 1)
    kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Tk - S), (0, 0)))
    vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Tk - S), (0, 0)))
    qp = jnp.pad(q_pos.astype(jnp.int32), ((0, 0), (0, Tq - T)))
    kp = jnp.pad(k_pos.astype(jnp.int32), ((0, 0), (0, Tk - S)),
                 constant_values=INVALID_POS)

    def q_map(b, n, i, j):
        return (b, n, i, 0)

    def kv_map(b, n, i, j, G=G):
        return (b, n // G, j, 0)

    in_specs = [
        pl.BlockSpec((1, 1, bq, H), q_map),
        pl.BlockSpec((1, 1, bk, H), kv_map),
        pl.BlockSpec((1, 1, bk, H), kv_map),
        pl.BlockSpec((1, 1, bq, 1), q_map),
        pl.BlockSpec((1, 1, 1, bk), lambda b, n, i, j: (b, 0, 0, j)),
    ]
    args = [qt, kt, vt,
            qp.reshape(B, 1, Tq, 1), kp.reshape(B, 1, 1, Tk)]
    if quant:
        # [B,Kv,S] -> [B,Kv,1,S]: 4-D form keeps the (1, bk) scale row a
        # real 2-D tile (the warm kernel's sublane trick)
        ks = jnp.pad(k_scale, ((0, 0), (0, 0), (0, Tk - S)))
        vs = jnp.pad(v_scale, ((0, 0), (0, 0), (0, Tk - S)))
        sc_map = functools.partial(lambda b, n, i, j, G=G: (b, n // G, 0, j))
        in_specs += [pl.BlockSpec((1, 1, 1, bk), sc_map),
                     pl.BlockSpec((1, 1, 1, bk), sc_map)]
        args += [ks.reshape(B, Kv, 1, Tk), vs.reshape(B, Kv, 1, Tk)]

    m, l, acc = pl.pallas_call(
        functools.partial(_ring_kernel, quant=quant),
        grid=(B, Nq, Tq // bq, Tk // bk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, 1), q_map),
            pl.BlockSpec((1, 1, bq, 1), q_map),
            pl.BlockSpec((1, 1, bq, H), q_map),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Nq, Tq, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Nq, Tq, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Nq, Tq, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),         # running max
            pltpu.VMEM((bq, 1), jnp.float32),         # running denom
            pltpu.VMEM((bq, H), jnp.float32),         # accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(*args)
    return (m[:, :, :T, 0], l[:, :, :T, 0], acc[:, :, :T])
