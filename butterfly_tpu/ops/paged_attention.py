"""Pallas paged attention: decode-step attention over the paged KV pool.

The decode-side hot kernel for continuous batching (BASELINE.json
configs[4]). The reference gather path (cache/paged.py gather_paged_layer)
materializes every slot's full [S_max] K/V view — reading null pages and
unallocated tail pages for short sequences. This kernel instead walks each
slot's block table and touches ONLY its live pages:

* `PrefetchScalarGridSpec(num_scalar_prefetch=2)`: the block table and
  lengths arrive before the body runs, so the K/V BlockSpec *index maps*
  dereference `table[slot, j]` — the DMA engine streams exactly the pages
  the slot owns, straight from HBM, double-buffered by the Mosaic
  pipeline. This is the TPU analogue of vLLM's CUDA paged-attention
  gather, with the page walk moved into the grid index maps.
* grid (slots, max_pages): per-slot online softmax across its pages
  (f32 scratch, same recurrence as ops/flash_attention.py); pages at or
  past the slot's length are predicated off with `pl.when` (their DMA
  still runs — at one page it is cheaper than a branchy pipeline).
* Decode has one query token per slot, so the MXU sees [Nq, H] x
  [H, page] per step — small, but the kernel is bandwidth-bound and reads
  ceil(len/page) pages instead of S_max.
* int8 pools: codes stream as-is (half the bytes — the entire point);
  per-vector scales ride along as one lane-aligned [Kv*page] row per
  page and fuse into the dots exactly like models.common.attend does for
  the contiguous int8 cache: K scales multiply the score columns, V
  scales fold into the probs. No dequantized copy is ever materialized.

Off-TPU the wrapper runs the kernel in interpreter mode (CPU tests cover
the exact kernel path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 names it TPUCompilerParams; alias so both resolve (the
# interpret-mode CPU tests otherwise die before interpretation starts)
if not hasattr(pltpu, "CompilerParams"):  # pragma: no cover
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30


def _block_update(s, mask, vf, m_ref, l_ref, acc_ref, vs_row):
    """One online-softmax accumulation step shared by the page blocks
    and the window segment: s [Nq, C] masked scores, vf [C, H] values,
    vs_row optional [1, C] V scales folded into the probs."""
    s = jnp.where(mask, s, NEG_INF)
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)       # [Nq, C]
    corr = jnp.exp(m_prev - m_new)
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    if vs_row is not None:
        p = p * vs_row                                 # V scale into probs
    acc_ref[:] = acc_ref[:] * corr + jnp.dot(
        p, vf, preferred_element_type=jnp.float32)
    m_ref[:] = m_new


def _paged_kernel(table_ref, len_ref, *rest, page: int, kv_heads: int,
                  quant: bool, window: int):
    """window > 0: one extra trailing grid step attends the slot's
    write-combined window segment [Kv, W, H] — staged-but-unflushed
    K/V at absolute positions length..length+win_count-1 — folded into
    the same online-softmax recurrence as the page blocks (the
    kv_write_combine serving path; cache/paged.py window docs)."""
    if window:
        wc_ref, *rest = rest
    q_ref, k_ref, v_ref, *rest = rest
    ks_ref = vs_ref = wk_ref = wv_ref = wks_ref = wvs_ref = None
    if quant:
        ks_ref, vs_ref, *rest = rest
    if window:
        wk_ref, wv_ref, *rest = rest
        if quant:
            wks_ref, wvs_ref, *rest = rest
    o_ref, m_ref, l_ref, acc_ref = rest
    slot = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    npages = nj - 1 if window else nj
    length = len_ref[slot]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when((j < npages) & (j * page < length))
    def _compute():
        # Mosaic-friendly GQA: ONE 2D matmul against the flattened
        # [Kv*page, H] block, with cross-group scores masked off. The
        # Kv-fold column redundancy is tiny (Kv*page cols) and keeps
        # everything on the plain MXU path (batched matmuls with
        # mismatched batch dims don't lower). The pool's [Kv, page, H]
        # block collapses its two leading dims for free (address
        # arithmetic only), so column c = kv*page + p — the same
        # kv-major order the flat scale rows use.
        q = q_ref[0].astype(jnp.float32)               # [Nq, H]
        kf = k_ref[0].astype(jnp.float32).reshape(kv_heads * page, -1)
        vf = v_ref[0].astype(jnp.float32).reshape(kv_heads * page, -1)
        Nq, H = q.shape
        G = Nq // kv_heads
        scale = jax.lax.rsqrt(jnp.asarray(H, jnp.float32))

        s = jnp.dot(q, kf.T, preferred_element_type=jnp.float32)
        if quant:
            # per-column K scale (scores = q . (codes*scale) done
            # output-side — same associativity as attend()). [1, C]
            # broadcasts over the Nq sublanes.
            s = s * ks_ref[0]
        s = s * scale
        cols = jax.lax.broadcasted_iota(jnp.int32, (Nq, kv_heads * page), 1)
        rows = jax.lax.broadcasted_iota(jnp.int32, (Nq, kv_heads * page), 0)
        col_kv, col_p = cols // page, cols % page
        group_ok = col_kv == rows // G                 # head n <-> kv n//G
        pos = j * page + col_p
        mask = group_ok & (pos < length)
        _block_update(s, mask, vf, m_ref, l_ref, acc_ref,
                      vs_ref[0] if quant else None)

    if window:
        @pl.when(j == nj - 1)
        def _window():
            # the window segment is one more "page" of width W at
            # positions >= length, masked by the slot's staged count —
            # identical recurrence, kv-major flat columns c = kv*W + w
            q = q_ref[0].astype(jnp.float32)
            kf = wk_ref[0].astype(jnp.float32).reshape(kv_heads * window, -1)
            vf = wv_ref[0].astype(jnp.float32).reshape(kv_heads * window, -1)
            Nq, H = q.shape
            G = Nq // kv_heads
            scale = jax.lax.rsqrt(jnp.asarray(H, jnp.float32))
            s = jnp.dot(q, kf.T, preferred_element_type=jnp.float32)
            if quant:
                s = s * wks_ref[0]
            s = s * scale
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (Nq, kv_heads * window), 1)
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (Nq, kv_heads * window), 0)
            col_kv, col_w = cols // window, cols % window
            mask = (col_kv == rows // G) & (col_w < wc_ref[slot])
            _block_update(s, mask, vf, m_ref, l_ref, acc_ref,
                          wvs_ref[0] if quant else None)

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def paged_attention_sharded(q: jax.Array, k_pages: jax.Array,
                            v_pages: jax.Array, page_table: jax.Array,
                            lengths: jax.Array,
                            k_scale_pages: jax.Array = None,
                            v_scale_pages: jax.Array = None,
                            win_k: jax.Array = None,
                            win_v: jax.Array = None,
                            win_count: jax.Array = None,
                            win_k_scale: jax.Array = None,
                            win_v_scale: jax.Array = None) -> jax.Array:
    """Mesh-aware paged attention for meshed serving (SURVEY.md §7 stage 6).

    shard_map over the axes the paged partitioner uses
    (parallel/partition.py paged_cache_specs): slots over `data`, q/kv
    heads over `tensor`; the page-id dim stays replicated (any slot may
    reference any page). A `tensor` shard of the flat [Kv*page] scale dim
    is the same contiguous kv-group chunk as the code pool's Kv shard, so
    one spec set covers both. Each shard walks its own slots' block
    tables with the unmodified kernel — purely local, no collectives.

    Returns None when a live multi-device Auto mesh is present but no
    axis can shard the operands — the caller must use the gather path
    (see flash_attention_sharded for the opaque-custom-call rationale);
    with no mesh at all this is exactly `paged_attention`.

    win_k/win_v [S, Kv, W, H] (+ win_k/v_scale [S, Kv, W] iff quant) +
    win_count [S]: the write-combined window segment (kv_write_combine)
    — slots shard over `data` with q/table/lengths, kv-heads over
    `tensor` with the pools.
    """
    from jax.sharding import PartitionSpec as P

    from butterfly_tpu.ops.flash_attention import (live_auto_mesh,
                                                   shardable_axes)

    S, Nq, H = q.shape
    Kv = k_pages.shape[1]          # pools are [P, Kv, page, H]
    d, t = shardable_axes(S, Nq, Kv)
    if d is None and t is None:
        if live_auto_mesh():
            return None
        return paged_attention(q, k_pages, v_pages, page_table, lengths,
                               k_scale_pages, v_scale_pages,
                               win_k=win_k, win_v=win_v,
                               win_count=win_count,
                               win_k_scale=win_k_scale,
                               win_v_scale=win_v_scale)
    kv_spec = P(None, t, None, None)
    in_specs = [P(d, t, None), kv_spec, kv_spec, P(d, None), P(d)]
    args = [q, k_pages, v_pages, page_table, lengths]
    if k_scale_pages is not None:
        in_specs += [P(None, t), P(None, t)]
        args += [k_scale_pages, v_scale_pages]
    if win_k is not None:
        win_spec = P(d, t, None, None)
        in_specs += [win_spec, win_spec, P(d)]
        args += [win_k, win_v, win_count]
        if win_k_scale is not None:
            in_specs += [P(d, t, None), P(d, t, None)]
            args += [win_k_scale, win_v_scale]

        def _kernel(*a):
            pos = a[:5] if k_scale_pages is None else a[:7]
            rest = a[len(pos):]
            kw = dict(win_k=rest[0], win_v=rest[1], win_count=rest[2])
            if len(rest) > 3:
                kw.update(win_k_scale=rest[3], win_v_scale=rest[4])
            return paged_attention(*pos, **kw)
        target = _kernel
    else:
        target = paged_attention
    fn = jax.shard_map(
        target,
        in_specs=tuple(in_specs),
        out_specs=P(d, t, None),
        axis_names={a for a in (d, t) if a is not None}, check_vma=False)
    return fn(*args)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_table: jax.Array, lengths: jax.Array,
                    k_scale_pages: jax.Array = None,
                    v_scale_pages: jax.Array = None,
                    win_k: jax.Array = None,
                    win_v: jax.Array = None,
                    win_count: jax.Array = None,
                    win_k_scale: jax.Array = None,
                    win_v_scale: jax.Array = None,
                    interpret: bool | None = None) -> jax.Array:
    """Single-token attention over each slot's paged KV.

    q: [slots, Nq, H] (the one decode token per slot, post-rope);
    k_pages/v_pages: [P, Kv, page, H] (one layer's pool);
    page_table: [slots, max_pages] int32; lengths: [slots] int32 —
    number of cache tokens INCLUDING the just-written current token;
    k/v_scale_pages: [P, Kv*page] f32 per-vector scales iff the pool
    holds int8 codes. Returns [slots, Nq, H].

    Write-combined window (kv_write_combine): win_k/win_v [S, Kv, W, H]
    hold each slot's staged-but-unflushed K/V (pool representation —
    int8 codes with win_k/v_scale [S, Kv, W] when the pool is
    quantized), at absolute positions lengths[s]..lengths[s] +
    win_count[s] - 1; `lengths` is then the FLUSHED pool length only
    and win_count INCLUDES the just-staged current token. The segment
    is one extra grid step folded into the same online-softmax
    recurrence as the page blocks (its DMA is one [Kv, W, H] block per
    slot — the staged run never round-trips through the pool).
    """
    S, Nq, H = q.shape
    Pp, Kv, page, H2 = k_pages.shape
    max_pages = page_table.shape[1]
    quant = k_scale_pages is not None
    window = 0 if win_k is None else win_k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # scalar-prefetch operands: (table, lengths[, win_count]) — the
    # index maps see them all; the pool maps clamp j to the page grid
    # (the trailing window step re-fetches the last page, unused)
    npre = 3 if window else 2

    def pool_map(s, j, t, ln, *wc):
        return (t[s, jnp.minimum(j, max_pages - 1)], 0, 0, 0)

    def pool_scale_map(s, j, t, ln, *wc):
        return (t[s, jnp.minimum(j, max_pages - 1)], 0, 0)

    def slot_map(s, j, t, ln, *wc):
        return (s, 0, 0)

    def win_map(s, j, t, ln, *wc):
        return (s, 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, Nq, H), slot_map),
        pl.BlockSpec((1, Kv, page, H), pool_map),
        pl.BlockSpec((1, Kv, page, H), pool_map),
    ]
    args = [q, k_pages, v_pages]
    if quant:
        # [P, C] -> [P, 1, C] (free bitcast): Mosaic requires the block's
        # minor-two dims to tile (8, 128) or equal the array's — a (1, C)
        # block of a [P, C] array does neither, but (1, 1, C) of
        # [P, 1, C] matches the array exactly.
        in_specs += [
            pl.BlockSpec((1, 1, Kv * page), pool_scale_map),
            pl.BlockSpec((1, 1, Kv * page), pool_scale_map),
        ]
        args += [k_scale_pages.reshape(Pp, 1, Kv * page),
                 v_scale_pages.reshape(Pp, 1, Kv * page)]
    if window:
        in_specs += [
            pl.BlockSpec((1, Kv, window, H), win_map),
            pl.BlockSpec((1, Kv, window, H), win_map),
        ]
        args += [win_k, win_v]
        if quant:
            in_specs += [
                pl.BlockSpec((1, 1, Kv * window), slot_map),
                pl.BlockSpec((1, 1, Kv * window), slot_map),
            ]
            args += [win_k_scale.reshape(S, 1, Kv * window),
                     win_v_scale.reshape(S, 1, Kv * window)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=npre,
        grid=(S, max_pages + (1 if window else 0)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Nq, H), slot_map),
        scratch_shapes=[
            pltpu.VMEM((Nq, 1), jnp.float32),
            pltpu.VMEM((Nq, 1), jnp.float32),
            pltpu.VMEM((Nq, H), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, page=page, kv_heads=Kv,
                               quant=quant, window=window)
    prefetch = [page_table, lengths]
    if window:
        prefetch.append(win_count)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Nq, H), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*prefetch, *args)
