"""Metrics time series (ISSUE 16): the bounded signal-history ring and
the declarative alert rules.

Every observability surface so far answers "what is the value NOW":
``metrics()`` is a point snapshot, ``/fleet/metrics`` a point rollup.
The self-tuning controller (ROADMAP item 6) and the autoscaler (item 2)
both need *trajectories* — a ramp is invisible in a single scrape. This
module is that sensing substrate:

* ``SignalRecorder`` — a bounded ring of periodic signal snapshots the
  scheduler loop thread samples every ``interval_s`` (``due()`` is one
  monotonic compare; a scheduler built without a recorder pays a single
  ``is None`` check per tick). Gauge signals are stored as-is;
  monotonic counters are passed as cumulative values and stored as
  per-second RATES (``Counter.rate`` deltas, clamped at zero so a
  counter reset — replica restart — never renders a negative rate).
  Served raw at ``GET /debug/timeseries?since=&signals=`` under its own
  lock, readable while the scheduler is wedged (the /debug/ticks
  contract).

* ``AlertRule`` — a declarative predicate over one signal's recent
  window: ``sustained_above`` (every sample in the window crossed),
  ``drift_above`` (recent-window mean minus prior-window mean),
  ``slope_below`` (least-squares slope per sample), ``flatline`` (a
  source stopped producing samples — fleet-side, driven by consecutive
  failed scrapes). Rules fire on the RISING edge only (one alert per
  excursion, not one per sample) and emit a structured ``alert`` event
  into the PR-15 flight recorder with the surrounding series attached,
  so a threshold crossing freezes its own post-mortem context.

Determinism contract (BTF005): this module never reads the wall clock —
ring ordering is by sequence number and ``time.monotonic()`` only, and
wall stamps are supplied by CALLERS (the scheduler/server, outside the
determinism scope) via the ``t_wall`` parameter. Host-only contract
(BTF003): ``sample`` / ``evaluate_rules`` do plain dict/float
arithmetic — no device value is ever materialized here.

stdlib-only: importable without jax (tools/dashboard.py consumes the
dumped JSON with no backend, like tick_report.py).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from butterfly_tpu.obs.registry import Counter

#: timeseries dump schema version (pinned by the dashboard smoke tests)
TIMESERIES_SCHEMA = "butterfly-timeseries-v1"
FLEET_TIMESERIES_SCHEMA = "butterfly-fleet-timeseries-v1"

#: alert predicate kinds (AlertRule.kind)
ALERT_KINDS = ("sustained_above", "drift_above", "slope_below",
               "flatline")


def slope_per_sample(values: Sequence[float]) -> float:
    """Least-squares slope of a series in signal-units PER SAMPLE
    (samples are interval-spaced, so units/second = this / interval).
    Plain host arithmetic over a short window."""
    n = len(values)
    if n < 2:
        return 0.0
    mx = (n - 1) / 2.0
    my = sum(values) / n
    num = sum((i - mx) * (v - my) for i, v in enumerate(values))
    den = sum((i - mx) ** 2 for i in range(n))
    return num / den if den else 0.0


class AlertRule:
    """One declarative predicate over one signal's recent window.

    ``window`` is the number of consecutive samples the predicate
    examines (``drift_above`` compares the last ``window`` against the
    ``window`` before it; ``flatline`` counts consecutive MISSING
    samples instead). ``threshold`` is in signal units
    (``slope_below``: units per sample). Rules are stateful — ``active``
    latches while the predicate holds so each excursion fires exactly
    one alert — and therefore must NOT be shared across sources; build
    one rule set per recorder / per replica (``default_rules()`` /
    ``default_fleet_rules()``).
    """

    __slots__ = ("name", "signal", "window", "kind", "threshold",
                 "severity", "active")

    def __init__(self, name: str, signal: str, window: int, kind: str,
                 threshold: float, severity: str = "warn"):
        if kind not in ALERT_KINDS:
            raise ValueError(f"unknown alert kind {kind!r}: "
                             f"expected one of {ALERT_KINDS}")
        if window < 1:
            raise ValueError(f"alert rule {name!r} needs window >= 1")
        self.name = name
        self.signal = signal
        self.window = int(window)
        self.kind = kind
        self.threshold = float(threshold)
        self.severity = severity
        self.active = False

    def describe(self) -> Dict[str, Any]:
        return {"rule": self.name, "signal": self.signal,
                "window": self.window, "kind": self.kind,
                "threshold": self.threshold, "severity": self.severity}


def default_rules() -> List[AlertRule]:
    """The seeded replica-side rule set: the error budget burning for a
    sustained window, the host share of tick wall drifting up (a host-
    path regression creeping in), and KV page headroom draining toward
    preemption pressure."""
    return [
        AlertRule("slo_burn_sustained", "slo_burn_rate", window=5,
                  kind="sustained_above", threshold=0.5, severity="page"),
        AlertRule("host_frac_drift", "tick_host_frac", window=8,
                  kind="drift_above", threshold=0.15, severity="warn"),
        AlertRule("pages_free_slope", "kv_pages_free", window=8,
                  kind="slope_below", threshold=-1.0, severity="warn"),
    ]


def default_fleet_rules() -> List[AlertRule]:
    """The seeded control-plane rule set, instantiated PER REPLICA
    (rules are stateful): a replica that stopped answering /metrics
    scrapes has flatlined — its gauges are about to be dropped from the
    /fleet/metrics re-export, and the autoscaler must hear about it."""
    return [
        AlertRule("replica_flatline", "scrape", window=3,
                  kind="flatline", threshold=3, severity="page"),
        AlertRule("pages_free_slope", "kv_pages_free", window=8,
                  kind="slope_below", threshold=-1.0, severity="warn"),
    ]


def evaluate_rules(rules: Sequence[AlertRule],
                   samples: Sequence[Dict[str, Any]],
                   flightrec=None, source: Optional[str] = None,
                   missing: int = 0) -> List[Dict[str, Any]]:
    """Evaluate every rule against the tail of ``samples`` (ring
    entries: dicts with a ``signals`` mapping). Fires on the RISING
    edge only; a fired rule stays ``active`` (silent) until its
    predicate releases. ``missing`` drives the ``flatline`` kind: the
    count of consecutive samples a source failed to produce.

    Each fired alert is returned AND noted into ``flightrec`` (event
    kind ``alert``) with the surrounding series attached — the post-
    mortem context the flight recorder freezes on its next trigger.
    Host-only dict/float arithmetic (BTF003 hot set)."""
    fired: List[Dict[str, Any]] = []
    for rule in rules:
        if rule.kind == "flatline":
            hot = missing >= rule.window
            value = float(missing)
            tail: List[float] = []
        else:
            tail = [float(s["signals"][rule.signal]) for s in samples
                    if rule.signal in s.get("signals", {})]
            hot, value = _series_predicate(rule, tail)
        if not hot:
            rule.active = False
            continue
        if rule.active:
            continue  # still in the same excursion: one alert, not N
        rule.active = True
        rec: Dict[str, Any] = dict(rule.describe())
        # the flight-recorder event kind is "alert"; the rule's
        # predicate kind rides under its own key
        rec["predicate"] = rec.pop("kind")
        rec["value"] = value
        rec["series"] = tail[-(2 * rule.window):]
        if source is not None:
            rec["source"] = source
        fired.append(rec)
        if flightrec is not None:
            flightrec.note("alert", **rec)
    return fired


def _series_predicate(rule: AlertRule, tail: List[float]):
    """(predicate holds, observed value) for the series-window kinds.
    A window shorter than the rule demands NEVER fires — one bad sample
    is a blip, not an alert (the mutcheck alert-predicate mutant
    weakens exactly this guard)."""
    if len(tail) < rule.window:
        return False, 0.0
    if rule.kind == "sustained_above":
        window = tail[-rule.window:]
        return all(v > rule.threshold for v in window), window[-1]
    if rule.kind == "drift_above":
        if len(tail) < 2 * rule.window:
            return False, 0.0
        recent = tail[-rule.window:]
        prior = tail[-2 * rule.window:-rule.window]
        drift = sum(recent) / len(recent) - sum(prior) / len(prior)
        return drift > rule.threshold, drift
    # slope_below
    slope = slope_per_sample(tail[-rule.window:])
    return slope < rule.threshold, slope


class SignalRecorder:
    """Bounded ring of periodic signal snapshots. One writer (the
    scheduler loop thread calls ``due()``/``sample()``), any number of
    readers (HTTP handlers call ``dump()``) — the ring takes a tiny
    internal lock, never the serving lock, so a wedged scheduler's
    history stays inspectable."""

    def __init__(self, interval_s: float = 1.0, capacity: int = 600,
                 rules: Optional[List[AlertRule]] = None,
                 flightrec=None, max_alerts: int = 64):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0 (a disabled "
                             "recorder is spelled timeseries=None)")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.rules = list(rules) if rules is not None else []
        self.flightrec = flightrec
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._alerts: deque = deque(maxlen=max_alerts)
        self._seq = 0
        # -inf sentinel: the first due() after construction samples
        # immediately (monotonic-only ordering — BTF005)
        self._last_t = float("-inf")
        # previous cumulative counter values + their monotonic stamp,
        # for the per-second rate deltas (None until the first sample)
        self._prev_rates: Dict[str, float] = {}
        self._prev_t: Optional[float] = None
        # how much tail the rule windows need (drift looks back 2x)
        self._rule_tail = max(
            [2 * r.window for r in self.rules], default=0)

    def due(self, now: Optional[float] = None) -> bool:
        """One float compare: is the next periodic sample owed? The
        scheduler's per-tick cost when a recorder is attached."""
        if now is None:
            now = time.monotonic()
        return now - self._last_t >= self.interval_s

    def sample(self, gauges: Dict[str, float],
               rates: Optional[Dict[str, float]] = None,
               t_wall: float = 0.0) -> List[Dict[str, Any]]:
        """Append one snapshot and evaluate the alert rules. ``gauges``
        are stored as-is; ``rates`` maps OUTPUT signal name ->
        CUMULATIVE counter value, converted to a per-second rate
        against the previous sample (``Counter.rate``: first sample and
        counter resets clamp to 0.0, never negative). ``t_wall`` is the
        caller's wall stamp — this module never reads the wall clock
        (BTF005), and the fleet merge shifts these stamps by the probe
        clock offset. Returns the alerts fired by this sample."""
        now = time.monotonic()
        signals = {k: float(v) for k, v in gauges.items()}
        if rates:
            prev_t = self._prev_t
            dt = now - prev_t if prev_t is not None else 0.0
            for name, cum in rates.items():
                signals[name] = Counter.rate(
                    self._prev_rates.get(name, 0.0), float(cum), dt) \
                    if prev_t is not None else 0.0
            self._prev_rates = {k: float(v) for k, v in rates.items()}
            self._prev_t = now
        entry = {"seq": self._seq, "t_mono": now,
                 "t_wall": float(t_wall), "signals": signals}
        with self._lock:
            self._ring.append(entry)
            self._seq += 1
            tail = list(self._ring)[-self._rule_tail:] \
                if self._rule_tail else []
        self._last_t = now
        fired = evaluate_rules(self.rules, tail,
                               flightrec=self.flightrec) \
            if self.rules else []
        if fired:
            with self._lock:
                for rec in fired:
                    self._alerts.append({"t_wall": float(t_wall),
                                         "seq": entry["seq"], **rec})
        return fired

    # -- read side -----------------------------------------------------------

    def dump(self, since: Optional[int] = None,
             signals: Optional[Sequence[str]] = None) -> Dict[str, Any]:
        """JSON-ready snapshot: the GET /debug/timeseries body.
        ``since`` pages by sequence number (samples with seq >= since —
        the /debug/ticks contract; a since older than the ring's tail
        returns what survived the wrap); ``signals`` filters each
        sample's signal map to the named set."""
        with self._lock:
            samples = list(self._ring)
            seq = self._seq
            alerts = list(self._alerts)
        if since is not None:
            samples = [s for s in samples if s["seq"] >= since]
        if signals:
            want = set(signals)
            samples = [{**s, "signals": {k: v
                                         for k, v in s["signals"].items()
                                         if k in want}}
                       for s in samples]
        return {"enabled": True, "schema": TIMESERIES_SCHEMA,
                "capacity": self.capacity, "interval_s": self.interval_s,
                "next_seq": seq, "rules": [r.describe()
                                           for r in self.rules],
                "samples": samples, "alerts": alerts}


def series_summary(dump: Dict[str, Any],
                   signals: Optional[Sequence[str]] = None) \
        -> Dict[str, Dict[str, float]]:
    """Downsample a timeseries dump to shape scalars per signal —
    peak/mean/slope (units per sample) plus the sample count — the
    summary the bench JSON carries so BENCH rounds record trajectory
    shape, not just endpoint values."""
    series: Dict[str, List[float]] = {}
    for s in dump.get("samples", ()):
        for k, v in s.get("signals", {}).items():
            if signals is None or k in signals:
                series.setdefault(k, []).append(float(v))
    return {k: {"peak": max(vals),
                "mean": sum(vals) / len(vals),
                "slope": slope_per_sample(vals),
                "n": float(len(vals))}
            for k, vals in sorted(series.items())}
