"""Observability: metrics exposition, typed instruments, tracing, health.

Only the stdlib-light modules are re-exported here (registry, trace,
metrics); benchmark/profile/health import jax and stay lazy.
"""
from butterfly_tpu.obs.metrics import (  # noqa: F401
    ThroughputWindow,
    render_prometheus,
)
from butterfly_tpu.obs.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from butterfly_tpu.obs.ticklog import (  # noqa: F401
    FlightRecorder,
    TickLog,
)
from butterfly_tpu.obs.trace import Tracer, summarize_timeline  # noqa: F401
