"""Per-request tracing: structured span events in a bounded ring.

Every request admitted to the scheduler gets a timeline of structured
events (submit, admit, prefill chunks, first token, preemption, finish)
plus a global ring of scheduler-tick and engine-dispatch events — the
per-request "where did the time go" view that aggregate percentiles
can't answer (Orca's per-iteration scheduling and vLLM's production
stack both lean on exactly this to debug tail latency; PAPERS.md).

Overhead contract: when tracing is off the scheduler holds ``trace =
None`` and every call site is a single attribute-is-None check — no
event objects, no locks, no timestamps. When on, an event is one
``time.monotonic()`` call plus an append to a bounded deque under an
uncontended lock (the scheduler thread is the only writer; HTTP readers
copy under the same lock).

Memory is bounded twice: at most ``max_requests`` per-request timelines
are retained (oldest evicted whole), and each timeline holds at most
``max_events_per_request`` events (a pathological 100k-token generation
cannot grow one timeline without bound). The global ring is a deque
with ``maxlen``.

stdlib-only: importable without jax (tools/trace_report.py runs on a
dumped trace with no backend).
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional


class Tracer:
    """Bounded in-memory trace store. One writer, many readers."""

    def __init__(self, max_requests: int = 256,
                 max_events_per_request: int = 512,
                 max_global_events: int = 4096):
        self.max_requests = max_requests
        self.max_events_per_request = max_events_per_request
        self._lock = threading.Lock()
        # rid -> {"id", "request_id", "events": deque, "done": bool}
        self._requests: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._global: deque = deque(maxlen=max_global_events)
        # anchor: monotonic timestamps in events convert to wall clock
        # via (t - t0_monotonic) + t0_wall when a report wants dates
        self.t0_monotonic = time.monotonic()
        self.t0_wall = time.time()

    # -- write side (scheduler / engine thread) -----------------------------

    def begin_request(self, rid: int,
                      request_id: Optional[str] = None, **attrs) -> None:
        """Open a timeline for request `rid` (the scheduler's req.id).
        `request_id` is the client-supplied passthrough id
        (X-Request-Id / body "request_id"), kept verbatim so client-side
        logs join against server traces."""
        rec = {"id": rid, "request_id": request_id, "done": False,
               "events": deque(maxlen=self.max_events_per_request)}
        with self._lock:
            # re-begin (same rid) replaces: ids are unique per scheduler
            self._requests[rid] = rec
            self._requests.move_to_end(rid)
            while len(self._requests) > self.max_requests:
                self._requests.popitem(last=False)
        self.event(rid, "submit", **attrs)

    def event(self, rid: Optional[int], name: str, **attrs) -> None:
        """Record one span event. rid=None -> the global ring (scheduler
        ticks, engine dispatches — events not owned by one request)."""
        ev = {"t": time.monotonic(), "name": name}
        if attrs:
            ev.update(attrs)
        with self._lock:
            if rid is None:
                self._global.append(ev)
                return
            rec = self._requests.get(rid)
            if rec is None:
                return  # evicted (or never begun): drop, never grow
            rec["events"].append(ev)
            if name == "finish":
                rec["done"] = True

    # -- read side (HTTP handlers / dump) -----------------------------------

    def timeline(self, rid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._requests.get(rid)
            if rec is None:
                return None
            return {"id": rec["id"], "request_id": rec["request_id"],
                    "done": rec["done"], "events": list(rec["events"])}

    def timelines(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Most recent `n` request timelines, oldest first."""
        with self._lock:
            recs = [{"id": r["id"], "request_id": r["request_id"],
                     "done": r["done"], "events": list(r["events"])}
                    for r in self._requests.values()]
        if n is not None and n >= 0:
            recs = recs[-n:]
        return recs

    def global_events(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._global)
        if n is not None and n >= 0:
            evs = evs[-n:]
        return evs

    def dump(self, n_requests: Optional[int] = None,
             n_global: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready snapshot: what /debug/requests returns and what
        tools/trace_report.py consumes."""
        return {
            "t0_monotonic": self.t0_monotonic,
            "t0_wall": self.t0_wall,
            "requests": self.timelines(n_requests),
            "global_events": self.global_events(n_global),
        }

    def dump_json(self, path: str, **kw) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(**kw), f)


def summarize_timeline(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Phase durations from one request's event list.

    Returns queue_wait_s (submit->admit), prefill_s (admit->prefill
    done), ttft_s (submit->first token), decode_s (first token->finish),
    total_s, plus token/preemption counts pulled off the events. Missing
    phases (aborted early, events evicted) come back as None — report
    code prints '-' rather than inventing zeros.
    """
    by_name: Dict[str, Dict[str, Any]] = {}
    preempts = 0
    chunks = 0
    for ev in rec.get("events", ()):
        name = ev.get("name")
        if name == "preempt":
            preempts += 1
        if name == "prefill_chunk":
            chunks += 1
        # keep the FIRST submit/admit/first_token and the LAST finish
        if name == "finish" or name not in by_name:
            by_name[name] = ev

    def t(name):
        ev = by_name.get(name)
        return ev["t"] if ev else None

    def delta(a, b):
        ta, tb = t(a), t(b)
        return (tb - ta) if ta is not None and tb is not None else None

    finish = by_name.get("finish", {})
    return {
        "id": rec.get("id"),
        "request_id": rec.get("request_id"),
        "state": finish.get("state",
                            "done" if rec.get("done") else "live"),
        "queue_wait_s": delta("submit", "admit"),
        "prefill_s": delta("admit", "prefill_done"),
        "ttft_s": delta("submit", "first_token"),
        "decode_s": delta("first_token", "finish"),
        "total_s": delta("submit", "finish"),
        "tokens": finish.get("tokens"),
        "prefill_chunks": chunks,
        "preemptions": preempts,
        "events": len(rec.get("events", ())),
    }
