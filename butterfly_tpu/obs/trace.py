"""Per-request tracing: structured span events in a bounded ring.

Every request admitted to the scheduler gets a timeline of structured
events (submit, admit, prefill chunks, first token, preemption, finish)
plus a global ring of scheduler-tick and engine-dispatch events — the
per-request "where did the time go" view that aggregate percentiles
can't answer (Orca's per-iteration scheduling and vLLM's production
stack both lean on exactly this to debug tail latency; PAPERS.md).

Overhead contract: when tracing is off the scheduler holds ``trace =
None`` and every call site is a single attribute-is-None check — no
event objects, no locks, no timestamps. When on, an event is one
``time.monotonic()`` call plus an append to a bounded deque under an
uncontended lock (the scheduler thread is the only writer; HTTP readers
copy under the same lock).

Memory is bounded twice: at most ``max_requests`` per-request timelines
are retained (oldest evicted whole), and each timeline holds at most
``max_events_per_request`` events (a pathological 100k-token generation
cannot grow one timeline without bound). The global ring is a deque
with ``maxlen``.

stdlib-only: importable without jax (tools/trace_report.py runs on a
dumped trace with no backend).
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional


class Tracer:
    """Bounded in-memory trace store. One writer, many readers."""

    def __init__(self, max_requests: int = 256,
                 max_events_per_request: int = 512,
                 max_global_events: int = 4096):
        self.max_requests = max_requests
        self.max_events_per_request = max_events_per_request
        self._lock = threading.Lock()
        # rid -> {"id", "request_id", "events": deque, "done": bool}
        self._requests: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self._global: deque = deque(maxlen=max_global_events)
        # anchor: monotonic timestamps in events convert to wall clock
        # via (t - t0_monotonic) + t0_wall when a report wants dates
        self.t0_monotonic = time.monotonic()
        self.t0_wall = time.time()

    # -- write side (scheduler / engine thread) -----------------------------

    def begin_request(self, rid: int,
                      request_id: Optional[str] = None, **attrs) -> None:
        """Open a timeline for request `rid` (the scheduler's req.id).
        `request_id` is the client-supplied passthrough id
        (X-Request-Id / body "request_id"), kept verbatim so client-side
        logs join against server traces."""
        rec = {"id": rid, "request_id": request_id, "done": False,
               "events": deque(maxlen=self.max_events_per_request)}
        with self._lock:
            # re-begin (same rid) replaces: ids are unique per scheduler
            self._requests[rid] = rec
            self._requests.move_to_end(rid)
            while len(self._requests) > self.max_requests:
                self._requests.popitem(last=False)
        self.event(rid, "submit", **attrs)

    def event(self, rid: Optional[int], name: str, **attrs) -> None:
        """Record one span event. rid=None -> the global ring (scheduler
        ticks, engine dispatches — events not owned by one request)."""
        ev = {"t": time.monotonic(), "name": name}
        if attrs:
            ev.update(attrs)
        with self._lock:
            if rid is None:
                self._global.append(ev)
                return
            rec = self._requests.get(rid)
            if rec is None:
                return  # evicted (or never begun): drop, never grow
            rec["events"].append(ev)
            if name == "finish":
                rec["done"] = True

    # -- read side (HTTP handlers / dump) -----------------------------------

    def timeline(self, rid: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._requests.get(rid)
            if rec is None:
                return None
            return {"id": rec["id"], "request_id": rec["request_id"],
                    "done": rec["done"], "events": list(rec["events"])}

    def timelines(self, n: Optional[int] = None,
                  request_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Most recent `n` request timelines, oldest first. `request_id`
        filters to timelines carrying that client id — the cross-replica
        join key: a fleet control plane asks each replica for exactly the
        timelines of ONE distributed request."""
        with self._lock:
            recs = [{"id": r["id"], "request_id": r["request_id"],
                     "done": r["done"], "events": list(r["events"])}
                    for r in self._requests.values()
                    if request_id is None or r["request_id"] == request_id]
        if n is not None and n >= 0:
            recs = recs[-n:] if n else []  # [-0:] would be the whole list
        return recs

    def find_by_request_id(self, request_id: str) -> Optional[Dict[str, Any]]:
        """Newest timeline tagged with `request_id` (newest wins: a
        retried client id maps to its latest attempt)."""
        recs = self.timelines(request_id=request_id)
        return recs[-1] if recs else None

    def global_events(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            evs = list(self._global)
        if n is not None and n >= 0:
            evs = evs[-n:] if n else []  # [-0:] would be the whole list
        return evs

    def dump(self, n_requests: Optional[int] = None,
             n_global: Optional[int] = None,
             request_id: Optional[str] = None) -> Dict[str, Any]:
        """JSON-ready snapshot: what /debug/requests returns and what
        tools/trace_report.py consumes. The `t0_wall`/`t0_monotonic`
        anchors let offline tools place every monotonic event timestamp
        on wall-clock time (and a fleet merge place several processes'
        events on ONE clock)."""
        return {
            "t0_monotonic": self.t0_monotonic,
            "t0_wall": self.t0_wall,
            "requests": self.timelines(n_requests, request_id=request_id),
            "global_events": self.global_events(n_global),
        }

    def dump_json(self, path: str, **kw) -> None:
        with open(path, "w") as f:
            json.dump(self.dump(**kw), f)


def summarize_timeline(rec: Dict[str, Any]) -> Dict[str, Any]:
    """Phase durations from one request's event list.

    Returns queue_wait_s (submit->admit), prefill_s (admit->prefill
    done), ttft_s (submit->first token), decode_s (first token->finish),
    total_s, plus token/preemption counts pulled off the events. Missing
    phases (aborted early, events evicted) come back as None — report
    code prints '-' rather than inventing zeros.
    """
    by_name: Dict[str, Dict[str, Any]] = {}
    preempts = 0
    chunks = 0
    for ev in rec.get("events", ()):
        name = ev.get("name")
        if name == "preempt":
            preempts += 1
        if name == "prefill_chunk":
            chunks += 1
        # keep the FIRST submit/admit/first_token and the LAST finish
        if name == "finish" or name not in by_name:
            by_name[name] = ev

    def t(name):
        ev = by_name.get(name)
        return ev["t"] if ev else None

    def delta(a, b):
        ta, tb = t(a), t(b)
        return (tb - ta) if ta is not None and tb is not None else None

    finish = by_name.get("finish", {})
    return {
        "id": rec.get("id"),
        "request_id": rec.get("request_id"),
        "state": finish.get("state",
                            "done" if rec.get("done") else "live"),
        "queue_wait_s": delta("submit", "admit"),
        "prefill_s": delta("admit", "prefill_done"),
        "ttft_s": delta("submit", "first_token"),
        "decode_s": delta("first_token", "finish"),
        "total_s": delta("submit", "finish"),
        "tokens": finish.get("tokens"),
        "prefill_chunks": chunks,
        "preemptions": preempts,
        "events": len(rec.get("events", ())),
    }


# -- fleet trace merging ------------------------------------------------------
#
# A disaggregated request crosses processes: the control plane runs the
# legs (classify, prefill_leg, kv_export, kv_import, decode_leg), each
# replica records its own per-request timeline. All timestamps are
# per-process time.monotonic(); each tracer's t0_wall/t0_monotonic
# anchors convert them to that PROCESS's wall clock, and a per-replica
# clock offset (estimated from the health-probe RTT midpoint,
# router/pool.py) places them on the control plane's clock:
#
#     t_cp_wall = t0_wall + (t - t0_monotonic) - offset_s
#
# where offset_s = replica_wall - control_wall at probe time. On one
# host the offsets are ~0; across hosts they absorb NTP skew down to
# half the probe RTT. Everything here is pure-dict stdlib so
# tools/trace_report.py renders a dumped merged trace with no backend.

def events_to_wall(events: List[Dict[str, Any]], t0_wall: float,
                   t0_monotonic: float,
                   offset_s: float = 0.0) -> List[Dict[str, Any]]:
    """Copy `events`, adding `t_wall` (control-plane wall clock)."""
    out = []
    for ev in events:
        ev2 = dict(ev)
        ev2["t_wall"] = t0_wall + (ev["t"] - t0_monotonic) - offset_s
        out.append(ev2)
    return out


def merge_fleet_trace(request_id: str, control: Dict[str, Any],
                      replicas: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Assemble one request's cross-replica waterfall.

    `control`: {"timeline": <Tracer timeline>, "t0_wall": ...,
    "t0_monotonic": ...} — the control plane's own span record.
    `replicas`: {rid: {"dump": <the /debug/requests?request_id= body,
    or None if unreachable>, "offset_s": float|None, "error": str}}.

    Returns the /fleet/trace body: `merged` (every event from every
    source on the control plane's wall clock, time-sorted, each tagged
    `source`), `legs` (control-plane spans with durations, waterfall
    order), and `sources` (per-source event counts; a missing replica
    degrades to control-plane spans only, with its error recorded).
    """
    cp_events = events_to_wall(control["timeline"].get("events", ()),
                               control["t0_wall"], control["t0_monotonic"])
    merged = [{**ev, "source": "control"} for ev in cp_events]
    sources: Dict[str, Dict[str, Any]] = {
        "control": {"events": len(cp_events), "offset_s": 0.0}}
    for rid, info in replicas.items():
        dump = info.get("dump")
        if not dump or not dump.get("requests"):
            sources[rid] = {"events": 0, "missing": True,
                            "offset_s": info.get("offset_s"),
                            "error": info.get("error",
                                              "no timeline for request")}
            continue
        offset = info.get("offset_s") or 0.0
        n = 0
        for rec in dump["requests"]:
            evs = events_to_wall(rec.get("events", ()),
                                 dump.get("t0_wall", 0.0),
                                 dump.get("t0_monotonic", 0.0), offset)
            merged.extend({**ev, "source": rid,
                           "replica_req": rec.get("id")} for ev in evs)
            n += len(evs)
        sources[rid] = {"events": n, "offset_s": offset,
                        "estimated_offset": info.get("offset_s") is not None}
    merged.sort(key=lambda ev: ev["t_wall"])
    # control-plane leg spans: events carrying dur_s were recorded at
    # leg END, so the span is [t_wall - dur_s, t_wall]
    legs = [{"name": ev["name"], "replica": ev.get("replica"),
             "start_wall": ev["t_wall"] - float(ev["dur_s"]),
             "end_wall": ev["t_wall"], "dur_s": float(ev["dur_s"]),
             **({"status": ev["status"]} if "status" in ev else {})}
            for ev in cp_events if "dur_s" in ev]
    legs.sort(key=lambda leg: leg["start_wall"])
    finish = next((ev for ev in reversed(cp_events)
                   if ev["name"] == "finish"), {})
    return {
        "request_id": request_id,
        "t0_wall": merged[0]["t_wall"] if merged else None,
        "total_s": finish.get("total_s"),
        "legs_total_s": sum(leg["dur_s"] for leg in legs),
        "legs": legs,
        "merged": merged,
        "sources": sources,
        "slo": {k: finish[k] for k in
                ("slo_ttft_ok", "slo_itl_ok", "ttft_s", "itl_mean_s")
                if k in finish} or None,
    }
