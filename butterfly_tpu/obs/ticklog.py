"""Tick anatomy (ISSUE 15): the per-tick timeline ring and the anomaly
flight recorder.

Two bounded, always-cheap instruments the scheduler feeds:

* ``TickLog`` — a ring of per-tick records: tick sequence number, wall
  time, per-phase host-section durations (the ``TICK_PHASES``
  vocabulary shared with docs/serving.md's tick-pipeline section),
  the stacked-fetch device wait, in-flight depth, barrier causes,
  batch occupancy and page headroom. One dict append per tick under an
  uncontended lock — the software answer to "where does the tick's
  host time go" that a TPU profile then confirms. Served raw at
  ``GET /debug/ticks`` and rendered by ``tools/tick_report.py``.

* ``FlightRecorder`` — a bounded ring of recent structured serving
  events (admission, preempt, shed, deadline 504, breaker transition,
  window flush, drain barrier, wedge) plus trigger predicates over
  per-tick signal snapshots. When a trigger fires (SLO burn rate over
  threshold, preemption storm, deadline-expiry burst, wedge latch) the
  recorder freezes the ring into a JSON post-mortem artifact —
  in-memory always, on disk when ``dump_dir`` is set — so the events
  LEADING UP to an anomaly survive the anomaly. Recording is
  deterministic: every event is kept (no sampling), bounded only by
  ``capacity``; the ``seed`` field rides the artifact so seeded soaks
  (fleet/chaos.py) can correlate artifacts with their fault plans.

stdlib-only: importable without jax (tools/tick_report.py consumes the
dumped JSON with no backend, like trace_report.py).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: The tick-phase vocabulary — one name per structural host section of
#: Scheduler.tick() (docs/serving.md cross-links these to the pipeline
#: steps). "other" is the measured residual (page prealloc, trace
#: appends), kept explicit so per-tick phase sums reconcile with tick
#: wall time instead of silently under-counting.
TICK_PHASES = ("expire", "drain_oldest", "drain_barrier", "admit",
               "assemble", "dispatch", "mixed", "spec_emit", "flush",
               "other")

#: Closed label set for drain_barriers_total{cause=...} — the
#: membership-change classes that force a FULL drain barrier.
BARRIER_CAUSES = ("admission", "finish", "page_pressure", "cancel",
                  "spec", "idle", "expired", "flush")


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile over a small list (stdlib; matches
    numpy's 'lower' interpolation closely enough for p50/p95 reports —
    the ticklog window is <= capacity entries)."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return float(s[idx])


class TickLog:
    """Bounded per-tick timeline ring. One writer (the scheduler
    thread), any number of readers (HTTP handlers) — record/dump take a
    tiny internal lock, never the serving lock, so a wedged scheduler
    can still be inspected."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    def record(self, wall_s: float, phases: Dict[str, float], *,
               fetch_s: float = 0.0, inflight: int = 0,
               barrier_causes=(), batch: int = 0, waiting: int = 0,
               pages_free: int = 0, generated: int = 0,
               spec: bool = False) -> None:
        """Append one tick record (hot path: one dict build + one
        locked append per TICK, never per token). `phases` is copied —
        callers may reuse/zero their accumulator dict."""
        entry = {
            "seq": self._seq,
            "t_wall": time.time(),
            "wall_s": wall_s,
            "phases": dict(phases),
            "fetch_s": fetch_s,
            "inflight": inflight,
            "barrier_causes": list(barrier_causes),
            "batch": batch,
            "waiting": waiting,
            "pages_free": pages_free,
            "generated": generated,
            "spec": spec,
        }
        with self._lock:
            self._ring.append(entry)
            self._seq += 1

    def dump(self, n: Optional[int] = None,
             since: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready snapshot: the GET /debug/ticks body and what
        tools/tick_report.py consumes. `since` pages by sequence
        number (ticks with seq >= since; a since older than the ring's
        tail returns what survived the wrap) — the incremental contract
        tick_report --follow polls on, applied before the `n` limit."""
        with self._lock:
            ticks = list(self._ring)
            seq = self._seq
        if since is not None:
            ticks = [t for t in ticks if t["seq"] >= since]
        if n is not None and n >= 0:
            ticks = ticks[-n:] if n else []
        return {"capacity": self.capacity, "next_seq": seq,
                "phases": list(TICK_PHASES), "ticks": ticks}

    def phase_percentiles(self) -> Dict[str, Dict[str, float]]:
        """Per-phase p50/p95 seconds over the ring window, plus the
        combined "drain" pseudo-phase (drain_oldest + drain_barrier per
        tick — the key bench.py reports)."""
        with self._lock:
            ticks = list(self._ring)
        if not ticks:
            return {}
        series: Dict[str, List[float]] = {}
        for t in ticks:
            ph = t["phases"]
            for name, v in ph.items():
                series.setdefault(name, []).append(v)
            series.setdefault("drain", []).append(
                ph.get("drain_oldest", 0.0) + ph.get("drain_barrier", 0.0))
        return {name: {"p50": percentile(vals, 50),
                       "p95": percentile(vals, 95)}
                for name, vals in series.items()}


#: flight-recorder artifact schema version (pinned by the chaos-soak
#: schema validation test)
FLIGHTREC_SCHEMA = "butterfly-flightrec-v1"


class FlightRecorder:
    """Bounded ring of structured serving events + anomaly triggers.

    ``note(kind, **attrs)`` appends one event (any thread; tiny lock).
    ``poll(signals)`` runs once per scheduler tick with a cheap signal
    snapshot and fires a dump when a trigger predicate crosses:

    * ``slo_burn_rate >= slo_burn_threshold`` — the error budget is
      burning (needs declared SLOs upstream to be nonzero);
    * preemption storm — ``preemptions_total`` grew by >=
      ``preempt_storm`` within ``window_s``;
    * deadline-expiry burst — ``deadline_expired_total`` grew by >=
      ``expiry_burst`` within ``window_s``;
    * wedge latch — the server calls ``trigger("wedge")`` directly from
      its heartbeat-failure hook (no polling: the tick loop may be the
      thing that died).

    A fired trigger freezes the ring into a JSON artifact (kept
    in-memory in ``dumps``, written to ``dump_dir`` when set) and then
    holds off for ``cooldown_s`` — one anomaly produces one artifact,
    not one per tick while the signal stays bad.
    """

    def __init__(self, capacity: int = 512, *, dump_dir: Optional[str] = None,
                 max_dumps: int = 4, slo_burn_threshold: float = 0.5,
                 preempt_storm: int = 8, expiry_burst: int = 4,
                 window_s: float = 10.0, cooldown_s: float = 30.0,
                 seed: int = 0):
        self.capacity = capacity
        self.dump_dir = dump_dir
        self.slo_burn_threshold = slo_burn_threshold
        self.preempt_storm = preempt_storm
        self.expiry_burst = expiry_burst
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        self.seed = seed
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self.dumps: deque = deque(maxlen=max_dumps)
        self.triggers_fired: Dict[str, int] = {}
        self._last_trigger_t = -1e18
        # (t_mono, value) samples for the burst detectors: the newest
        # sample OLDER than window_s is the baseline (the counter's
        # value as of the window start). Seeded with (now, 0.0) —
        # counters start at zero, so growth before the first poll
        # still counts toward the first window's burst.
        now = time.monotonic()
        self._preempt_win: deque = deque([(now, 0.0)])
        self._expiry_win: deque = deque([(now, 0.0)])

    # -- event ring ----------------------------------------------------------

    def note(self, kind: str, **attrs) -> None:
        """Append one structured event. Cheap enough for per-admission/
        per-barrier call sites; callers hold no other lock."""
        ev = {"seq": self._seq, "t_wall": time.time(),
              "t_mono": time.monotonic(), "kind": kind}
        if attrs:
            ev.update(attrs)
        with self._lock:
            self._ring.append(ev)
            self._seq += 1

    # -- triggers ------------------------------------------------------------

    def _burst(self, win: deque, now: float, value: float,
               threshold: int) -> bool:
        win.append((now, value))
        # prune to the window, but always retain the NEWEST sample
        # older than it: that is the counter's value at the window
        # start, the honest baseline (dropping it would make the first
        # in-window sample the baseline and under-count the burst)
        while len(win) >= 2 and win[1][0] < now - self.window_s:
            win.popleft()
        return value - win[0][1] >= threshold

    def poll(self, signals: Dict[str, float]) -> Optional[Dict[str, Any]]:
        """Per-tick trigger evaluation (a few float compares; no
        allocation on the no-trigger path beyond the window deques).
        Returns the dumped artifact when a trigger fired, else None."""
        now = time.monotonic()
        reason = None
        burn = signals.get("slo_burn_rate", 0.0)
        if burn >= self.slo_burn_threshold and burn > 0.0:
            reason = "slo_burn"
        if self._burst(self._preempt_win, now,
                       signals.get("preemptions_total", 0.0),
                       self.preempt_storm):
            reason = reason or "preempt_storm"
        if self._burst(self._expiry_win, now,
                       signals.get("deadline_expired_total", 0.0),
                       self.expiry_burst):
            reason = reason or "expiry_burst"
        if reason is None:
            return None
        if now - self._last_trigger_t < self.cooldown_s:
            return None  # cooldown: one artifact per anomaly
        return self.trigger(reason, signals)

    def trigger(self, reason: str,
                signals: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
        """Freeze the ring into a post-mortem artifact NOW (also the
        direct entry point for the wedge latch). Always returns the
        artifact; writes it to dump_dir when configured."""
        self._last_trigger_t = time.monotonic()
        with self._lock:
            events = list(self._ring)
            seq = self._seq
        counts: Dict[str, int] = {}
        for ev in events:
            counts[ev["kind"]] = counts.get(ev["kind"], 0) + 1
        artifact: Dict[str, Any] = {
            "schema": FLIGHTREC_SCHEMA,
            "reason": reason,
            "seed": self.seed,
            "t_wall": time.time(),
            "t_mono": time.monotonic(),
            "next_seq": seq,
            "signals": dict(signals or {}),
            "event_counts": counts,
            "events": events,
        }
        self.triggers_fired[reason] = self.triggers_fired.get(reason, 0) + 1
        if self.dump_dir:
            try:
                import os
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"flightrec-{seq}-{reason}.json")
                with open(path, "w") as f:
                    json.dump(artifact, f)
                artifact["path"] = path
            except OSError as e:  # disk trouble must not wedge serving
                artifact["path_error"] = f"{type(e).__name__}: {e}"
        self.dumps.append(artifact)
        return artifact

    # -- read side -----------------------------------------------------------

    def dump(self, n: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready snapshot: the GET /debug/flightrecorder body
        (current ring + the retained trigger artifacts)."""
        with self._lock:
            events = list(self._ring)
            seq = self._seq
        if n is not None and n >= 0:
            events = events[-n:] if n else []
        return {"enabled": True, "capacity": self.capacity,
                "next_seq": seq, "seed": self.seed,
                "triggers_fired": dict(self.triggers_fired),
                "events": events, "dumps": list(self.dumps)}
