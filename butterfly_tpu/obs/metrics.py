"""Prometheus-format metrics for the serving endpoint.

Reports BASELINE.json's metrics of record directly (tokens/sec/chip, TTFT
percentiles, queue depth, KV-page occupancy — SURVEY.md §5). The reference
only ever *planned* observability (/root/reference/CLAUDE.md:42).

Two layers feed /metrics:

* the legacy flat dict from ``Scheduler.metrics()`` (gauges + the
  window-percentile snapshot keys), rendered here;
* the typed instrument registry (obs/registry.py) — counters and
  fixed-bucket histograms (``ttft_seconds``, ``itl_req_mean_seconds``,
  ``queue_wait_seconds``, ...) with real ``_bucket``/``_sum``/``_count``
  exposition. When both layers carry the same name the registry wins
  (it has the authoritative TYPE and atomic reads).
"""
from __future__ import annotations

import time
from typing import Dict, Optional


PREFIX = "butterfly"

# NB (ADVICE.md round 5 / ISSUE 10): with pipelined decode dispatch,
# tokens surface in per-tick stacked-drain BURSTS, so the raw-gap ITL
# percentiles bimodalize (p50 identically 0.0 between burst-mates at
# decode_steps_per_tick > 1) and ttft_* includes up to one extra tick
# of drain delay. The degenerate bare itl_p50/itl_p95 keys were DROPPED
# (r05 published itl_p50: 0.0 as a headline number); the raw-gap values
# survive only under the explicit *_tick_burst suffix. The ITL metrics
# of record are itl_req_mean_* (per-request mean gap) and the
# butterfly_ttft_seconds / butterfly_itl_req_mean_seconds histograms.
HELP = {
    "requests_total": "Requests submitted",
    "requests_finished": "Requests completed",
    "tokens_generated_total": "Tokens generated across all requests",
    "preemptions_total": "Recompute preemptions under page pressure",
    "queue_depth": "Requests waiting for a slot",
    "active_requests": "Requests currently decoding",
    "kv_pages_free": "Free KV-cache pages",
    "kv_pages_total": "Total usable KV-cache pages",
    "ttft_p50": "p50 time-to-first-token (seconds; stamped at the "
                "stacked drain, so includes up to one tick of burst "
                "delay — see ttft_seconds histogram)",
    "ttft_p95": "p95 time-to-first-token (seconds; stamped at the "
                "stacked drain — see ttft_seconds histogram)",
    "itl_p50_tick_burst": "p50 raw inter-token gap (seconds; PER-TICK-"
                          "BURST semantics under pipelined dispatch — "
                          "identically 0.0 between burst-mates; prefer "
                          "itl_req_mean_p50)",
    "itl_p95_tick_burst": "p95 raw inter-token gap (seconds; PER-TICK-"
                          "BURST semantics under pipelined dispatch — "
                          "prefer itl_req_mean_p95)",
    "itl_max_tick_burst": "max raw inter-token gap in the recent window "
                          "(seconds; per-tick-burst semantics)",
    "itl_req_mean_p50": "p50 over finished requests of each request's "
                        "MEAN inter-token gap (seconds) — the "
                        "effective streaming rate a client experiences",
    "itl_req_mean_p95": "p95 over finished requests of each request's "
                        "MEAN inter-token gap (seconds)",
    "tokens_per_sec": "Decode throughput over the last window",
    "uptime_seconds": "Server uptime",
    "prefix_cache_hit_tokens": "Prompt tokens served from the prefix cache",
    "prefix_cache_lookup_tokens": "Prompt tokens looked up in the prefix cache",
    "tick_host_frac": "Fraction of tick wall time spent in host "
                      "sections (1 - tick_device_frac): the "
                      "host-bound-vs-device-bound autoscale signal "
                      "(ISSUE 15 tick anatomy)",
    "tick_device_frac": "Fraction of tick wall time blocked on the "
                        "stacked device fetch",
    "tick_phase_dominant_p95": "p95 seconds of the largest tick phase "
                               "over the timeline-ring window — which "
                               "host term dominates (see "
                               "/debug/ticks and tools/tick_report.py)",
}

COUNTERS = {"requests_total", "requests_finished", "tokens_generated_total",
            "preemptions_total", "prefix_cache_hit_tokens",
            "prefix_cache_lookup_tokens"}


class ThroughputWindow:
    """Sliding-window tokens/sec estimate, host-side, O(1) amortized."""

    def __init__(self, window_s: float = 10.0):
        import threading
        from collections import deque
        self.window_s = window_s
        self._events = deque()  # (t, ntokens)
        # record() runs on the scheduler thread, rate() on HTTP handlers
        self._lock = threading.Lock()

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def record(self, ntokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, ntokens))
            self._prune(now)

    def rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-6)
            return sum(n for _, n in self._events) / span


def render_prometheus(values: Dict[str, float],
                      registry: Optional[object] = None) -> str:
    """Dict (+ optional MetricsRegistry) -> prometheus exposition text.

    Registry instruments render with full histogram series; dict keys
    that collide with a registry instrument name are skipped so the
    output never emits a metric name twice (the text format forbids it).
    """
    skip = set(registry.names()) if registry is not None else ()
    lines = []
    for name, val in sorted(values.items()):
        if name in skip:
            continue
        full = f"{PREFIX}_{name}"
        if isinstance(val, str):
            # String-valued annotations (e.g. spec_mixed_fallback_reason)
            # ride along as comments: the exposition format has no string
            # samples, and parsers ignore non-HELP/TYPE comment lines.
            lines.append(f"# {full}: {val}")
            continue
        if name in HELP:
            lines.append(f"# HELP {full} {HELP[name]}")
            kind = "counter" if name in COUNTERS else "gauge"
            lines.append(f"# TYPE {full} {kind}")
        lines.append(f"{full} {float(val):g}")
    text = "\n".join(lines) + "\n" if lines else ""
    if registry is not None:
        text += registry.render()
    return text
