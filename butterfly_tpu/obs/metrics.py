"""Prometheus-format metrics for the serving endpoint.

Reports BASELINE.json's metrics of record directly (tokens/sec/chip, TTFT
percentiles, queue depth, KV-page occupancy — SURVEY.md §5). The reference
only ever *planned* observability (/root/reference/CLAUDE.md:42).
"""
from __future__ import annotations

import time
from typing import Dict


PREFIX = "butterfly"

HELP = {
    "requests_total": "Requests submitted",
    "requests_finished": "Requests completed",
    "tokens_generated_total": "Tokens generated across all requests",
    "preemptions_total": "Recompute preemptions under page pressure",
    "queue_depth": "Requests waiting for a slot",
    "active_requests": "Requests currently decoding",
    "kv_pages_free": "Free KV-cache pages",
    "kv_pages_total": "Total usable KV-cache pages",
    "ttft_p50": "p50 time-to-first-token (seconds)",
    "ttft_p95": "p95 time-to-first-token (seconds)",
    "itl_p50": "p50 inter-token latency (seconds)",
    "itl_p95": "p95 inter-token latency (seconds)",
    "itl_max": "max inter-token latency in the recent window (seconds)",
    "tokens_per_sec": "Decode throughput over the last window",
    "uptime_seconds": "Server uptime",
    "prefix_cache_hit_tokens": "Prompt tokens served from the prefix cache",
    "prefix_cache_lookup_tokens": "Prompt tokens looked up in the prefix cache",
}

COUNTERS = {"requests_total", "requests_finished", "tokens_generated_total",
            "preemptions_total", "prefix_cache_hit_tokens",
            "prefix_cache_lookup_tokens"}


class ThroughputWindow:
    """Sliding-window tokens/sec estimate, host-side, O(1) amortized."""

    def __init__(self, window_s: float = 10.0):
        import threading
        from collections import deque
        self.window_s = window_s
        self._events = deque()  # (t, ntokens)
        # record() runs on the scheduler thread, rate() on HTTP handlers
        self._lock = threading.Lock()

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def record(self, ntokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, ntokens))
            self._prune(now)

    def rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-6)
            return sum(n for _, n in self._events) / span


def render_prometheus(values: Dict[str, float]) -> str:
    """Dict -> prometheus exposition text."""
    lines = []
    for name, val in sorted(values.items()):
        full = f"{PREFIX}_{name}"
        if name in HELP:
            lines.append(f"# HELP {full} {HELP[name]}")
            kind = "counter" if name in COUNTERS else "gauge"
            lines.append(f"# TYPE {full} {kind}")
        lines.append(f"{full} {float(val):g}")
    return "\n".join(lines) + "\n"
