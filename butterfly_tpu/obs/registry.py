"""Typed metrics registry: Counter / Gauge / Histogram instruments.

Replaces the scheduler's ad-hoc ``Dict[str, float]`` with real
instruments so /metrics can expose *distributions* — fixed-bucket
Prometheus histograms with ``_bucket``/``_sum``/``_count`` series —
instead of deque-percentile snapshots whose semantics silently shift
with the emission pattern (ADVICE.md round 5: deferred emission skews
the raw itl_p50/p95 keys).

Threading contract: ONE writer thread (the scheduler loop owns every
inc()/observe(); the server's tick loop is the only thread that ticks),
any number of reader threads (HTTP /metrics handlers). Counters and
gauges are plain float slots — a read may be one update stale, never
torn (CPython). Histograms take a small lock so a scrape never sees
``_sum``/``_count`` disagree with the bucket totals; observe() runs
per-request/per-tick, not per-token, so the lock is off the hot path.

stdlib-only: importable without jax (tools/trace_report.py and the
format tests run without a backend).
"""
from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

# Fixed bucket ladders. Latency buckets span sub-ms host work up to a
# minute of queueing; token/batch ladders are powers of two matching the
# prefill bucketing (engine.serving.bucket_len) and slot counts.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)
TOKEN_BUCKETS: Tuple[float, ...] = (
    16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def sanitize_name(name: str) -> str:
    """Coerce to a legal Prometheus metric name ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    name = _NAME_BAD_CHARS.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    """Prometheus float formatting ('+Inf' never reaches here)."""
    return f"{float(v):g}"


class Counter:
    """Monotonic counter. Single-writer; inc() only goes up."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self._value += n

    @property
    def value(self) -> float:
        return self._value

    @staticmethod
    def rate(prev: float, curr: float, dt: float) -> float:
        """Per-second rate between two snapshots of a monotonic
        counter, CLAMPED at 0.0: a restarted process re-exposes the
        counter from zero, and a negative "rate" across that reset is
        an artifact, not a signal (the SignalRecorder's delta path —
        obs/timeseries.py — leans on this clamp)."""
        if dt <= 0.0:
            return 0.0
        return max(0.0, (curr - prev) / dt)

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} counter")
        out.append(f"{full} {_fmt(self._value)}")
        return out


class Gauge:
    """Settable instantaneous value."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    def dec(self, n: float = 1.0) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} gauge")
        out.append(f"{full} {_fmt(self._value)}")
        return out


class Histogram:
    """Fixed-bucket histogram with Prometheus exposition semantics.

    ``_bucket{le="x"}`` series are CUMULATIVE and end with ``le="+Inf"``
    == ``_count``; ``_sum`` is the total of observed values. Buckets are
    fixed at construction — no dynamic rebucketing, so a long-lived
    server's series never change shape under a dashboard.
    """

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        bs = [float(b) for b in buckets]
        if bs != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"bucket bounds must be strictly increasing: "
                             f"{buckets}")
        self.name = name
        self.help = help
        self.buckets = tuple(bs)
        # per-bucket (non-cumulative) counts; the +Inf overflow is last
        self._counts = [0] * (len(bs) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        # linear scan: the ladders are ~10-16 entries and observe() runs
        # per-request / per-tick — bisect would be noise
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — atomic."""
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum, running = [], 0
        for n in counts:
            running += n
            cum.append(running)
        return cum, s, c

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        cum, s, c = self.snapshot()
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} histogram")
        for bound, n in zip(self.buckets, cum):
            out.append(f'{full}_bucket{{le="{_fmt(bound)}"}} {n}')
        out.append(f'{full}_bucket{{le="+Inf"}} {cum[-1]}')
        out.append(f"{full}_sum {_fmt(s)}")
        out.append(f"{full}_count {c}")
        return out


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class LabeledFamily:
    """A family of Counter/Gauge children keyed by label values.

    ``labels(...)`` get-or-creates the child for one label-value tuple;
    the child is a plain Counter/Gauge (same single-writer contract), and
    the family renders HELP/TYPE once followed by every child as a
    ``name{label="value",...}`` series. Children are never retired — the
    router's label sets (replica id x outcome) are small and fixed, so a
    long-lived process can't leak series without leaking replicas.
    """

    __slots__ = ("cls", "name", "help", "labelnames", "_children", "_lock")

    def __init__(self, cls, name: str, help: str,
                 labelnames: Sequence[str]):
        self.cls = cls
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        if not self.labelnames:
            raise ValueError(f"family {name} needs at least one label")
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, *values):
        vals = tuple(str(v) for v in values)
        if len(vals) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {len(vals)} values")
        with self._lock:
            child = self._children.get(vals)
            if child is None:
                child = self.cls(self.name)
                self._children[vals] = child
            return child

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        with self._lock:
            items = sorted(self._children.items())
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        kind = "counter" if self.cls is Counter else "gauge"
        out.append(f"# TYPE {full} {kind}")
        for vals, child in items:
            lbl = ",".join(f'{n}="{_escape_label(v)}"'
                           for n, v in zip(self.labelnames, vals))
            out.append(f"{full}{{{lbl}}} {_fmt(child.value)}")
        return out


class MetricsRegistry:
    """Named instrument registry with idempotent get-or-create.

    ``counter``/``gauge``/``histogram`` return the existing instrument
    when the (sanitized) name is already registered — callers in
    different layers can share an instrument by name without plumbing
    object references through the stack.
    """

    def __init__(self, prefix: str = "butterfly"):
        self.prefix = prefix
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        name = sanitize_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def _family(self, cls, name: str, help: str,
                labelnames: Sequence[str]) -> LabeledFamily:
        name = sanitize_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = LabeledFamily(cls, name, help, labelnames)
                self._instruments[name] = inst
            elif not (isinstance(inst, LabeledFamily) and inst.cls is cls
                      and inst.labelnames == tuple(labelnames)):
                raise ValueError(
                    f"metric {name!r} already registered with a different "
                    f"type or label set")
            return inst

    def counter_family(self, name: str, help: str = "",
                       labelnames: Sequence[str] = ()) -> LabeledFamily:
        return self._family(Counter, name, help, labelnames)

    def gauge_family(self, name: str, help: str = "",
                     labelnames: Sequence[str] = ()) -> LabeledFamily:
        return self._family(Gauge, name, help, labelnames)

    def names(self) -> Iterable[str]:
        with self._lock:
            return set(self._instruments)

    def get(self, name: str):
        return self._instruments.get(sanitize_name(name))

    def value_dict(self) -> Dict[str, float]:
        """Counter/gauge values as a flat dict (the legacy metrics()
        shape; histograms are exposition-only and skipped)."""
        with self._lock:
            insts = list(self._instruments.values())
        return {i.name: i.value for i in insts
                if isinstance(i, (Counter, Gauge))}

    def snapshot(self) -> Dict[str, float]:
        """Cheap name -> value snapshot for periodic sampling (the
        SignalRecorder's per-interval read): plain counters/gauges as
        their value, labeled families as the SUM over their children
        (the per-label split stays on the exposition surface — a rate
        series wants the total). Float reads only; no rendering."""
        with self._lock:
            insts = list(self._instruments.values())
        out: Dict[str, float] = {}
        for i in insts:
            if isinstance(i, (Counter, Gauge)):
                out[i.name] = i.value
            elif isinstance(i, LabeledFamily):
                with i._lock:
                    out[i.name] = sum(
                        c.value for c in i._children.values())
        return out

    def render(self) -> str:
        """Prometheus exposition text for every instrument."""
        with self._lock:
            insts = sorted(self._instruments.items())
        lines: List[str] = []
        for _, inst in insts:
            lines.extend(inst.render(self.prefix))
        return "\n".join(lines) + ("\n" if lines else "")


# -- exposition parsing + fleet aggregation ----------------------------------
#
# The fleet control plane scrapes each replica's /metrics text and
# re-exports a rollup (GET /fleet/metrics): counters sum exactly, and
# because every replica's histograms use the SAME fixed bucket ladders
# (above), summing the cumulative per-le bucket series is an EXACT
# re-bucketing — no interpolation, no resolution loss. Gauges do not
# aggregate meaningfully by summation (uptime, queue depth snapshots),
# so the rollup drops them; the control plane re-exposes the autoscale
# gauges per replica with a {replica=...} label instead.

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> Dict[str, Dict]:
    """Parse Prometheus text into families.

    Returns ``{family_name: {"type": kind, "help": str, "samples":
    {(series_name, labels): value}}}`` where ``labels`` is a sorted
    tuple of (label, value) pairs. The ``_bucket``/``_sum``/``_count``
    series of a ``# TYPE name histogram`` family fold under the family
    name. Unparseable lines are skipped (scrapes must never fail on a
    foreign exporter's extension).
    """
    families: Dict[str, Dict] = {}
    types: Dict[str, str] = {}

    def fam(name: str) -> Dict:
        f = families.get(name)
        if f is None:
            f = families[name] = {"type": types.get(name, "untyped"),
                                  "help": "", "samples": {}}
        return f

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
                fam(parts[2])["type"] = parts[3].strip()
            elif len(parts) >= 4 and parts[1] == "HELP":
                fam(parts[2])["help"] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        series, raw_labels, raw_val = m.groups()
        try:
            value = float(raw_val)
        except ValueError:
            continue
        name = series
        for suffix in ("_bucket", "_sum", "_count"):
            base = series[:-len(suffix)] if series.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                name = base
                break
        labels = tuple(sorted(
            (k, _unescape_label(v))
            for k, v in _LABEL_RE.findall(raw_labels or "")))
        fam(name)["samples"][(series, labels)] = value
    return families


def _bucket_ladder(family: Dict) -> frozenset:
    """The set of `le` bounds a parsed histogram family exposes."""
    return frozenset(
        dict(labels).get("le") for series, labels in family["samples"]
        if series.endswith("_bucket"))


def sum_expositions(parsed: Sequence[Dict[str, Dict]]) -> Dict[str, Dict]:
    """Merge parsed expositions from N processes into one rollup.

    Counter samples sum per (series, labels); histogram families sum
    their cumulative bucket/_sum/_count series — exact when every
    process exposes the same ladder, and a family whose ladders
    DISAGREE across processes is dropped entirely (a partial sum would
    render a histogram whose +Inf != _count). Gauge and untyped
    families are dropped (see module comment).
    """
    out: Dict[str, Dict] = {}
    dropped: set = set()
    for p in parsed:
        for name, family in p.items():
            kind = family["type"]
            if kind not in ("counter", "histogram") or name in dropped:
                continue
            agg = out.get(name)
            if agg is None:
                agg = out[name] = {"type": kind, "help": family["help"],
                                   "samples": {}}
            if kind == "histogram" and agg["samples"] and \
                    _bucket_ladder(agg) != _bucket_ladder(family):
                del out[name]
                dropped.add(name)
                continue
            for key, v in family["samples"].items():
                agg["samples"][key] = agg["samples"].get(key, 0.0) + v
    return out


def render_parsed(families: Dict[str, Dict],
                  rename=None) -> List[str]:
    """Parsed/aggregated families back to exposition lines. `rename`
    maps a family name to its exported name (the fleet rollup namespaces
    `butterfly_*` as `butterfly_fleet_*`); series suffixes and labels
    are preserved."""
    lines: List[str] = []
    for name in sorted(families):
        family = families[name]
        new = rename(name) if rename is not None else name
        if family["help"]:
            lines.append(f"# HELP {new} {family['help']}")
        lines.append(f"# TYPE {new} {family['type']}")
        for (series, labels), v in sorted(family["samples"].items()):
            s = new + series[len(name):]
            if labels:
                lbl = ",".join(f'{k}="{_escape_label(v2)}"'
                               for k, v2 in labels)
                s += "{" + lbl + "}"
            # bucket/count series render as integers when whole
            lines.append(f"{s} {_fmt(v)}")
        # histogram series order: render() above sorts _bucket lines by
        # the stringified le bound — fine for consumers that key on the
        # le label (Prometheus does), and stable across scrapes
    return lines
