"""Typed metrics registry: Counter / Gauge / Histogram instruments.

Replaces the scheduler's ad-hoc ``Dict[str, float]`` with real
instruments so /metrics can expose *distributions* — fixed-bucket
Prometheus histograms with ``_bucket``/``_sum``/``_count`` series —
instead of deque-percentile snapshots whose semantics silently shift
with the emission pattern (ADVICE.md round 5: deferred emission skews
the raw itl_p50/p95 keys).

Threading contract: ONE writer thread (the scheduler loop owns every
inc()/observe(); the server's tick loop is the only thread that ticks),
any number of reader threads (HTTP /metrics handlers). Counters and
gauges are plain float slots — a read may be one update stale, never
torn (CPython). Histograms take a small lock so a scrape never sees
``_sum``/``_count`` disagree with the bucket totals; observe() runs
per-request/per-tick, not per-token, so the lock is off the hot path.

stdlib-only: importable without jax (tools/trace_report.py and the
format tests run without a backend).
"""
from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

# Fixed bucket ladders. Latency buckets span sub-ms host work up to a
# minute of queueing; token/batch ladders are powers of two matching the
# prefill bucketing (engine.serving.bucket_len) and slot counts.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
BATCH_BUCKETS: Tuple[float, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)
TOKEN_BUCKETS: Tuple[float, ...] = (
    16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 16384)


def sanitize_name(name: str) -> str:
    """Coerce to a legal Prometheus metric name ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    name = _NAME_BAD_CHARS.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    """Prometheus float formatting ('+Inf' never reaches here)."""
    return f"{float(v):g}"


class Counter:
    """Monotonic counter. Single-writer; inc() only goes up."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        self._value += n

    @property
    def value(self) -> float:
        return self._value

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} counter")
        out.append(f"{full} {_fmt(self._value)}")
        return out


class Gauge:
    """Settable instantaneous value."""

    __slots__ = ("name", "help", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self._value += n

    def dec(self, n: float = 1.0) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} gauge")
        out.append(f"{full} {_fmt(self._value)}")
        return out


class Histogram:
    """Fixed-bucket histogram with Prometheus exposition semantics.

    ``_bucket{le="x"}`` series are CUMULATIVE and end with ``le="+Inf"``
    == ``_count``; ``_sum`` is the total of observed values. Buckets are
    fixed at construction — no dynamic rebucketing, so a long-lived
    server's series never change shape under a dashboard.
    """

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        bs = [float(b) for b in buckets]
        if bs != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"bucket bounds must be strictly increasing: "
                             f"{buckets}")
        self.name = name
        self.help = help
        self.buckets = tuple(bs)
        # per-bucket (non-cumulative) counts; the +Inf overflow is last
        self._counts = [0] * (len(bs) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        # linear scan: the ladders are ~10-16 entries and observe() runs
        # per-request / per-tick — bisect would be noise
        i = 0
        for i, b in enumerate(self.buckets):
            if v <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count) — atomic."""
        with self._lock:
            counts = list(self._counts)
            s, c = self._sum, self._count
        cum, running = [], 0
        for n in counts:
            running += n
            cum.append(running)
        return cum, s, c

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        cum, s, c = self.snapshot()
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        out.append(f"# TYPE {full} histogram")
        for bound, n in zip(self.buckets, cum):
            out.append(f'{full}_bucket{{le="{_fmt(bound)}"}} {n}')
        out.append(f'{full}_bucket{{le="+Inf"}} {cum[-1]}')
        out.append(f"{full}_sum {_fmt(s)}")
        out.append(f"{full}_count {c}")
        return out


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class LabeledFamily:
    """A family of Counter/Gauge children keyed by label values.

    ``labels(...)`` get-or-creates the child for one label-value tuple;
    the child is a plain Counter/Gauge (same single-writer contract), and
    the family renders HELP/TYPE once followed by every child as a
    ``name{label="value",...}`` series. Children are never retired — the
    router's label sets (replica id x outcome) are small and fixed, so a
    long-lived process can't leak series without leaking replicas.
    """

    __slots__ = ("cls", "name", "help", "labelnames", "_children", "_lock")

    def __init__(self, cls, name: str, help: str,
                 labelnames: Sequence[str]):
        self.cls = cls
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        if not self.labelnames:
            raise ValueError(f"family {name} needs at least one label")
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def labels(self, *values):
        vals = tuple(str(v) for v in values)
        if len(vals) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, "
                f"got {len(vals)} values")
        with self._lock:
            child = self._children.get(vals)
            if child is None:
                child = self.cls(self.name)
                self._children[vals] = child
            return child

    def render(self, prefix: str) -> List[str]:
        full = f"{prefix}_{self.name}" if prefix else self.name
        with self._lock:
            items = sorted(self._children.items())
        out = []
        if self.help:
            out.append(f"# HELP {full} {self.help}")
        kind = "counter" if self.cls is Counter else "gauge"
        out.append(f"# TYPE {full} {kind}")
        for vals, child in items:
            lbl = ",".join(f'{n}="{_escape_label(v)}"'
                           for n, v in zip(self.labelnames, vals))
            out.append(f"{full}{{{lbl}}} {_fmt(child.value)}")
        return out


class MetricsRegistry:
    """Named instrument registry with idempotent get-or-create.

    ``counter``/``gauge``/``histogram`` return the existing instrument
    when the (sanitized) name is already registered — callers in
    different layers can share an instrument by name without plumbing
    object references through the stack.
    """

    def __init__(self, prefix: str = "butterfly"):
        self.prefix = prefix
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str, **kw):
        name = sanitize_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, help, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, not {cls.__name__}")
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def _family(self, cls, name: str, help: str,
                labelnames: Sequence[str]) -> LabeledFamily:
        name = sanitize_name(name)
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = LabeledFamily(cls, name, help, labelnames)
                self._instruments[name] = inst
            elif not (isinstance(inst, LabeledFamily) and inst.cls is cls
                      and inst.labelnames == tuple(labelnames)):
                raise ValueError(
                    f"metric {name!r} already registered with a different "
                    f"type or label set")
            return inst

    def counter_family(self, name: str, help: str = "",
                       labelnames: Sequence[str] = ()) -> LabeledFamily:
        return self._family(Counter, name, help, labelnames)

    def gauge_family(self, name: str, help: str = "",
                     labelnames: Sequence[str] = ()) -> LabeledFamily:
        return self._family(Gauge, name, help, labelnames)

    def names(self) -> Iterable[str]:
        with self._lock:
            return set(self._instruments)

    def get(self, name: str):
        return self._instruments.get(sanitize_name(name))

    def value_dict(self) -> Dict[str, float]:
        """Counter/gauge values as a flat dict (the legacy metrics()
        shape; histograms are exposition-only and skipped)."""
        with self._lock:
            insts = list(self._instruments.values())
        return {i.name: i.value for i in insts
                if isinstance(i, (Counter, Gauge))}

    def render(self) -> str:
        """Prometheus exposition text for every instrument."""
        with self._lock:
            insts = sorted(self._instruments.items())
        lines: List[str] = []
        for _, inst in insts:
            lines.extend(inst.render(self.prefix))
        return "\n".join(lines) + ("\n" if lines else "")
