"""Profiling/tracing hooks (SURVEY.md §5: jax.profiler + named scopes).

The reference only *planned* observability (/root/reference/CLAUDE.md:42);
the TPU-native mechanism is XProf: `trace()` captures a TensorBoard-
loadable profile of any code region (XLA ops, Pallas kernels, collectives,
host activity), `start_profiler_server()` enables on-demand capture from
a live serving process, and `step_timer` is a zero-dependency host-side
ring buffer for per-tick latency percentiles.
"""
from __future__ import annotations

import contextlib
import sys
import time
from collections import deque
from typing import Dict, Iterator, Optional

import numpy as np


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XProf trace of the enclosed region into `logdir`."""
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


#: the live ProfilerServer (jax returns a handle that must stay
#: referenced; dropping it would stop the server)
_PROFILER_SERVER = None


def start_profiler_server(port: int = 9999) -> bool:
    """On-demand profiling for live servers (connect with TensorBoard/
    XProf). Returns True when listening. Failure — jax without the
    profiler plugin (ImportError), the port already bound, a second
    start in one process — logs a warning and returns False instead of
    crashing the serve entrypoint (`serve --profiler-port` is an
    observability convenience, never worth taking the replica down)."""
    global _PROFILER_SERVER
    try:
        import jax
        _PROFILER_SERVER = jax.profiler.start_server(port)
        return True
    except ImportError as e:
        print(f"[butterfly] profiler server unavailable (no xprof): {e}",
              file=sys.stderr, flush=True)
        return False
    except Exception as e:  # port in use / double start / backend quirk
        print(f"[butterfly] profiler server failed to start on :{port}: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)
        return False


def annotate(name: str):
    """Named region: shows up in XProf timelines (jax.named_scope)."""
    import jax
    return jax.named_scope(name)


class StepTimer:
    """Host-side ring buffer of step latencies -> percentiles."""

    def __init__(self, capacity: int = 1024):
        self._lat = deque(maxlen=capacity)

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._lat.append(time.perf_counter() - t0)

    def percentiles(self) -> Dict[str, float]:
        if not self._lat:
            return {}
        a = np.asarray(self._lat)
        return {"step_p50_s": float(np.percentile(a, 50)),
                "step_p95_s": float(np.percentile(a, 95)),
                "step_p99_s": float(np.percentile(a, 99)),
                "steps_recorded": float(len(a))}
