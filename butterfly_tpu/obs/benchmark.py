"""Shared decode-throughput benchmark (used by bench.py and `butterfly bench`).

Reports both raw tokens/sec and tokens/sec/chip (the BASELINE.json metric
of record); one implementation so the two entrypoints can't drift.
"""
from __future__ import annotations

import time
from typing import Dict

import numpy as np


def run_decode_benchmark(model, params, batch: int, prompt_len: int,
                         max_new: int, seed: int = 0) -> Dict:
    import jax
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine import InferenceEngine, SamplingParams

    engine = InferenceEngine(
        model, params, RuntimeConfig(max_seq_len=prompt_len + max_new))
    rng = np.random.RandomState(seed)
    prompts = rng.randint(1, model.cfg.vocab_size,
                          (batch, prompt_len)).tolist()
    sp = SamplingParams(max_new_tokens=max_new)

    engine.generate(prompts, sp)  # compile + warmup
    t0 = time.perf_counter()
    engine.generate(prompts, sp)
    dt = time.perf_counter() - t0

    n_chips = max(1, len(jax.devices()))
    total = batch * max_new
    return {
        "tokens_per_sec": total / dt,
        "tokens_per_sec_per_chip": total / dt / n_chips,
        "decode_seconds": dt,
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": max_new,
        "n_chips": n_chips,
    }
