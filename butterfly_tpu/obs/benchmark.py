"""Shared decode-throughput benchmark (used by bench.py and `butterfly bench`).

Reports raw tokens/sec, tokens/sec/chip (the BASELINE.json metric of
record), and a roofline utilization estimate: decode is HBM-bandwidth
bound (every step streams all weights + the KV cache), so

    hbm_util = bytes_streamed_per_step * decode_steps_per_sec / HBM_BW

is the fraction of the chips' usable bandwidth the decode loop sustains.
Weights replicated over the `data` mesh axis are streamed once *per
replica* (each chip reads its own copy), so bytes_per_step scales with
the data-parallel degree. Decode time is isolated by subtracting a
max_new=1 run (prefill + first sample) from the full run, so prefill
cost doesn't dilute the number. One implementation so the entrypoints
can't drift.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

# Usable HBM bandwidth per chip, bytes/sec. v5e: ~819 GB/s.
HBM_BW = {"TPU v5 lite": 819e9, "TPU v5e": 819e9, "TPU v4": 1228e9,
          "TPU v5p": 2765e9, "TPU v6 lite": 1640e9, "TPU v6e": 1640e9}
DEFAULT_HBM_BW = 819e9
# bf16 dense peak matmul throughput per chip, FLOP/s, per device kind.
PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v4": 275e12,
              "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12}
DEFAULT_PEAK_FLOPS = 197e12


def _chip_lookup(table: Dict[str, float], default: float) -> float:
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "")
    for k, v in table.items():
        if k.lower() in kind.lower():
            return v
    return default


def run_decode_benchmark(model, params, batch: int, prompt_len: int,
                         max_new: int, seed: int = 0,
                         mesh=None, kv_quant: str = "none") -> Dict:
    import jax
    import jax.numpy as jnp
    from butterfly_tpu.core.config import RuntimeConfig
    from butterfly_tpu.engine import InferenceEngine, SamplingParams

    engine = InferenceEngine(
        model, params, RuntimeConfig(max_seq_len=prompt_len + max_new,
                                     kv_quant=kv_quant),
        mesh=mesh)
    rng = np.random.RandomState(seed)
    prompts = rng.randint(1, model.cfg.vocab_size,
                          (batch, prompt_len)).tolist()
    sp = SamplingParams(max_new_tokens=max_new)
    sp1 = SamplingParams(max_new_tokens=1)

    engine.generate(prompts, sp1)   # compile prefill + first sample
    engine.generate(prompts, sp)    # compile fused decode scan

    t0 = time.perf_counter()
    engine.generate(prompts, sp1)
    t_prefill = time.perf_counter() - t0

    t0 = time.perf_counter()
    engine.generate(prompts, sp)
    dt = time.perf_counter() - t0

    decode_steps = max_new - 1      # steps taken by the fused scan
    decode_dt = max(dt - t_prefill, 1e-9)
    steps_per_sec = decode_steps / decode_dt

    # Roofline accounting: every decode step streams the full weight tree
    # and reads the whole KV cache buffer (k + v). An unmeshed engine runs
    # on exactly one chip regardless of how many the host exposes; a
    # meshed engine uses mesh.size chips and streams one weight copy per
    # data-parallel replica.
    cfg = model.cfg
    leaves = jax.tree.leaves(engine.params)
    param_bytes = sum(x.nbytes for x in leaves)
    param_count = sum(x.size for x in leaves)
    S = prompt_len + max_new
    # bytes per stored K/V vector: head_dim * itemsize, +4 for the f32
    # per-vector scale in int8 mode
    vec_bytes = cfg.head_dim * (1 if kv_quant == "int8"
                                else jnp.dtype(cfg.dtype).itemsize) \
        + (4 if kv_quant == "int8" else 0)
    kv_bytes = 2 * cfg.num_layers * batch * S * cfg.num_kv_heads * vec_bytes
    n_chips = mesh.size if mesh is not None else 1
    dp = mesh.shape.get("data", 1) if mesh is not None else 1
    bytes_per_step = param_bytes * dp + kv_bytes
    hbm_util = (bytes_per_step * steps_per_sec /
                (_chip_lookup(HBM_BW, DEFAULT_HBM_BW) * n_chips))
    # Decode matmul FLOPs ~= 2 * weight params * batch per step.
    mfu = (2 * param_count * batch * steps_per_sec /
           (_chip_lookup(PEAK_FLOPS, DEFAULT_PEAK_FLOPS) * n_chips))

    total = batch * max_new
    return {
        "tokens_per_sec": total / dt,
        "tokens_per_sec_per_chip": total / dt / n_chips,
        "decode_tokens_per_sec": batch * steps_per_sec,
        "decode_tokens_per_sec_per_chip": batch * steps_per_sec / n_chips,
        "hbm_util": hbm_util,
        "mfu": mfu,
        "decode_seconds": decode_dt,
        "prefill_seconds": t_prefill,
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": max_new,
        "n_chips": n_chips,
    }
